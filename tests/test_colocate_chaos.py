"""Colocation survival: train + serve + bulk on ONE cluster, under
chaos and overcommit (ISSUE-20 tentpole, cluster half).

The scenario ROADMAP item 1 calls the framework's reason to exist: a
JaxTrainer DCN gang (collective class), a two-tenant LLMPool (kv
class), and periodic checkpoint shipping (bulk class) share the same
agents while the ``colocate`` chaos profile fires across the pacer,
decode pumps, ring sends, and checkpoint writes. Both SLO floors must
hold SIMULTANEOUSLY: the gang converges with zero cold restarts and
every tenant's TTFT stays bounded, while bulk completes.

Separately, a 2x-overcommitted pool must walk the overload guardian's
ladder to L3, shed admissions TYPED-RETRYABLE (lowest-weight tenant
first), keep the surviving tenant inside its TTFT floor, and — once
the flood stops — recover to L0 without oscillating.
"""

import json
import sys
import threading
import time

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import config as _cfg
from ray_tpu._private import fault_injection as fi
from ray_tpu._private.chaos import gen_fault_plan
from ray_tpu.cluster_utils import Cluster
from ray_tpu.serve.llm_pool import LLMPool
from ray_tpu.serve.overload import (
    DeadlineExceededError,
    PoolOverloadedError,
)
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

# worker subprocesses can't import the tests package: ship helpers by value
cloudpickle.register_pickle_by_value(sys.modules[__name__])

N_BLOCKS = 8
DIM = 16
LR = 0.1
STEPS = 5
WORLD = 2

# fixed tier-1 colocate seed: rank-0 ring.send exit at occurrence 0
# (immediate gang kill -> in-place resume) PLUS a decode-1 pump exit
# (replica death under tenant load) — both classes take a hit at once
SMOKE_SEEDS = (9,)
SMOKE_DEADLINE_S = 180.0
SOAK_SEEDS = tuple(range(0, 16))
SOAK_DEADLINE_S = 240.0


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()
    _cfg.set_system_config({"fault_spec": ""})


def _block_grad(i, step):
    rng = np.random.default_rng(7919 * (i + 1) + step)
    return rng.standard_normal(DIM).astype(np.float32)


def _ref_params(steps):
    p = np.zeros(DIM, np.float32)
    for s in range(steps):
        total = np.zeros(DIM, np.float32)
        for i in range(N_BLOCKS):
            total = total + _block_grad(i, s)
        p = p - LR * (total / N_BLOCKS)
    return p


def _colo_loop(config):
    """Same world-size-invariant training as the chaos soak (any
    elastic trajectory produces identical parameters), running while a
    serving pool and bulk ships contend for the same cluster."""
    import os as _os

    import numpy as _np

    from ray_tpu._private import fault_injection as _fi
    from ray_tpu.train import dcn_allreduce_grads, session
    from ray_tpu.train.checkpoint import Checkpoint as _Ck

    rank = session.get_world_rank()
    seq = session.get_resume_seq()
    if seq == 0 and config.get("worker_specs"):
        _fi.configure(config["worker_specs"])
    shard = session.get_dataset_shard("train")
    group = session.get_collective_group()
    params = _np.zeros(DIM, _np.float32)
    start = 0
    ck = session.get_checkpoint()
    if ck is not None:
        d = ck.to_dict()
        params = _np.asarray(d["params"], _np.float32)
        start = int(d["step"])
    for step in range(start, config["steps"]):
        contrib = _np.zeros(DIM, _np.float32)
        for i in shard.assigned_indices():
            contrib = contrib + _block_grad(i, step)
        total = dcn_allreduce_grads({"g": contrib}, group, op="sum",
                                    timeout=10.0)["g"]
        params = params - LR * (total / N_BLOCKS)
        ckpt = None
        if rank == 0:
            ckpt = _Ck.from_dict(
                {"step": step + 1, "params": params},
                _os.path.join(config["ck_dir"], f"ck_s{seq}_{step}"))
        session.report({"step": step + 1,
                        "loss": float(_np.square(params).sum())},
                       checkpoint=ckpt)


class _ServeLoad:
    """Two tenants hammering the pool from threads until stopped.
    Typed-retryable sheds are counted, not failures; anything else is
    a failure."""

    def __init__(self, pool, tenants=("A", "B"), threads_per=2,
                 new_tokens=16):
        self.pool = pool
        self.stop = threading.Event()
        self.errs: list[str] = []
        self.sheds = 0
        self.done = 0
        self._threads = [
            threading.Thread(target=self._one, args=(tn, k),
                             daemon=True)
            for tn in tenants for k in range(threads_per)
        ]
        self.new_tokens = new_tokens

    def _one(self, tenant, k):
        rng = np.random.RandomState(hash((tenant, k)) % 2**31)
        while not self.stop.is_set():
            prompt = [int(x) for x in rng.randint(1, 250, 12)]
            try:
                out = self.pool.generate(prompt, self.new_tokens,
                                         tenant=tenant)
                assert len(out["tokens"]) == self.new_tokens
                self.done += 1
            except PoolOverloadedError as e:
                assert e.retryable and e.retry_after_s > 0
                self.sheds += 1
                time.sleep(min(e.retry_after_s, 0.5))
            except Exception as e:  # noqa: BLE001
                self.errs.append(
                    f"{tenant}/{k}: {type(e).__name__}: {e}")
                return

    def start(self):
        for t in self._threads:
            t.start()

    def finish(self, timeout=60):
        self.stop.set()
        for t in self._threads:
            t.join(timeout=timeout)


class _BulkShips:
    """Periodic checkpoint ship+fetch round-trips (the bulk class)."""

    def __init__(self, tmp_path):
        self.tmp = tmp_path
        self.stop = threading.Event()
        self.completed = 0
        self.errs: list[str] = []
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        from ray_tpu.train.checkpoint import Checkpoint, ship_checkpoint

        i = 0
        while not self.stop.is_set():
            try:
                ck = Checkpoint.from_dict(
                    {"step": i, "blob": np.zeros(64_000, np.uint8)},
                    str(self.tmp / f"ship_{i}"))
                ref = ship_checkpoint(ck)
                out = ray_tpu.get(ref, timeout=120)
                assert out["members"]
                self.completed += 1
            except Exception as e:  # noqa: BLE001
                self.errs.append(f"ship {i}: {type(e).__name__}: {e}")
            i += 1
            self.stop.wait(1.0)

    def start(self):
        self._t.start()

    def finish(self, timeout=130):
        self.stop.set()
        self._t.join(timeout=timeout)


def _run_colocate_seed(cluster, tmp_path, seed: int, deadline_s: float):
    plan = gen_fault_plan(seed, world_size=WORLD, max_faults=2,
                          profile="colocate", n_replicas=2)
    fi.clear()
    if plan.driver_specs:
        fi.configure(plan.driver_specs)
    # decode replicas arm via the env-propagated spec: set BEFORE spawn
    _cfg.set_system_config({
        "fault_spec": json.dumps(plan.serve_specs)
        if plan.serve_specs else ""})
    out = tmp_path / f"colo{seed}"
    out.mkdir()
    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=8, prompt_buckets=(16,),
                   min_replicas=2, max_replicas=2, chunk_delay_s=0.02,
                   autoscale=True,
                   tenant_weights={"A": 2.0, "B": 1.0})
    load = _ServeLoad(pool)
    ships = _BulkShips(out)
    trainer = JaxTrainer(
        _colo_loop,
        train_loop_config={
            "steps": STEPS,
            "ck_dir": str(out / "ckpts"),
            "worker_specs": plan.worker_specs,
        },
        scaling_config=ScalingConfig(
            num_workers=WORLD, resources_per_worker={"CPU": 1},
            backend="dcn", min_workers=1, placement_strategy="PACK",
        ),
        run_config=RunConfig(
            name=f"colo{seed}", storage_path=str(out),
            max_failures=4, max_inplace_resumes=12,
        ),
        datasets={"train": list(range(N_BLOCKS))},
    )
    t0 = time.monotonic()
    try:
        # warm every replica's jit cache before measuring TTFT: compile
        # time is a cold-start cost, not a colocation cost
        warm = [int(x) for x in
                np.random.RandomState(5).randint(1, 250, 12)]
        ray_tpu.get([r.handle.generate.remote(warm, 8)
                     for r in pool._alive()], timeout=600)
        load.start()
        ships.start()
        result = trainer.fit()
        train_s = time.monotonic() - t0
        # keep contending until every class has proof of life (the
        # tiny-model pool spends its first seconds jit-compiling, so
        # the serve side may lag a fast training run)
        while ((ships.completed < 2 or load.done < 8
                or pool.ttft_p99("A") is None
                or pool.ttft_p99("B") is None)
               and not load.errs and not ships.errs
               and time.monotonic() - t0 < deadline_s):
            time.sleep(0.5)
        load.finish()
        ships.finish()
        elapsed = time.monotonic() - t0

        # -- training floor: converged, exact, ZERO gang restarts --
        assert result.error is None, result.error
        assert result.metrics["step"] == STEPS, result.metrics
        ref = _ref_params(STEPS)
        np.testing.assert_allclose(
            np.asarray(result.checkpoint.to_dict()["params"]), ref,
            rtol=1e-5, atol=1e-6)
        assert result.resumes["gang"] == 0, result.resumes
        assert train_s < deadline_s, (
            f"seed {seed} train took {train_s:.1f}s: {plan.describe()}")

        # -- serve floor: both tenants served, TTFT bounded, typed
        # errors only --
        assert not load.errs, load.errs[0]
        assert load.done >= 8, (load.done, load.sheds)
        for tn in ("A", "B"):
            p99 = pool.ttft_p99(tn)
            assert p99 is not None, f"tenant {tn} never served"
            assert p99 < 8.0, f"tenant {tn} TTFT p99 {p99:.2f}s"

        # -- bulk floor: ships completed despite the squeeze window --
        assert not ships.errs, ships.errs[0]
        assert ships.completed >= 2, ships.completed

        # the guardian rode along (ladder state visible to operators)
        assert pool.stats()["overload"] is not None
        return result, load, ships, elapsed
    except BaseException:
        print(f"\nCOLOCATE CHAOS FAILURE {plan.describe()}\n"
              f"replay: RAY_TPU_FAULT_SPEC='{plan.env_value()}'\n",
              file=sys.stderr, flush=True)
        raise
    finally:
        load.stop.set()
        ships.stop.set()
        pool.shutdown()
        fi.clear()
        _cfg.set_system_config({"fault_spec": ""})


def test_colocate_smoke(cluster, tmp_path):
    """Tier-1: one fixed colocate seed — immediate gang rank kill plus
    a decode-replica pump death — with both SLO floors asserted while
    checkpoint ships complete."""
    for seed in SMOKE_SEEDS:
        result, load, ships, elapsed = _run_colocate_seed(
            cluster, tmp_path, seed, SMOKE_DEADLINE_S)
        print(f"colocate seed {seed}: {elapsed:.1f}s "
              f"resumes={result.resumes} served={load.done} "
              f"sheds={load.sheds} ships={ships.completed}")


@pytest.mark.slow
def test_colocate_soak_randomized(cluster, tmp_path):
    """The sweep: every colocate-profile seed must keep both floors."""
    report = []
    for seed in SOAK_SEEDS:
        result, load, ships, elapsed = _run_colocate_seed(
            cluster, tmp_path, seed, SOAK_DEADLINE_S)
        report.append((seed, round(elapsed, 1), result.resumes,
                       load.done, load.sheds, ships.completed))
    print("\ncolocate soak (seed, s, resumes, served, sheds, ships):")
    for row in report:
        print(f"  {row}")
    assert len(report) == len(SOAK_SEEDS)


# ---------------------------------------------------------------------------
# 2x overcommit: ladder to L3, typed sheds, survivor floor, L0 recovery
# ---------------------------------------------------------------------------

FAST_KNOBS = {
    "overload_escalate_dwell_s": 0.2,
    "overload_recover_dwell_s": 0.3,
    "overload_queue_per_replica_high": 2.0,
    "overload_shed_queue_bound": 8,
}
def _restore_overload_knobs():
    _cfg.set_system_config({
        "overload_escalate_dwell_s": 1.0,
        "overload_recover_dwell_s": 3.0,
        "overload_queue_per_replica_high": 8.0,
        "overload_shed_queue_bound": 64,
    })


def test_overcommit_sheds_typed_and_recovers(cluster):
    """A single-replica pool flooded at ~2x its admission capacity must
    escalate to L3, refuse overflow TYPED-RETRYABLE (lowest-weight
    tenant first — the high-weight tenant's p99 stays floored), and
    after the flood stops walk back to L0 without oscillating."""
    from ray_tpu._private import flight_recorder as _fr

    _cfg.set_system_config(dict(FAST_KNOBS))
    # max_inflight 2 makes the admission queue the contended resource:
    # 8 flood threads against 2 slots is the 2x+ overcommit
    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=8, prompt_buckets=(16,),
                   min_replicas=1, max_replicas=1, chunk_delay_s=0.05,
                   max_inflight_per_replica=2, autoscale=True,
                   tenant_weights={"gold": 4.0, "bronze": 1.0})
    stop = threading.Event()
    shed_errs: list[PoolOverloadedError] = []
    hard_errs: list[str] = []
    ok = {"gold": 0, "bronze": 0}
    lock = threading.Lock()

    def flood(tenant, k):
        rng = np.random.RandomState(42 + k)
        while not stop.is_set():
            prompt = [int(x) for x in rng.randint(1, 250, 12)]
            try:
                out = pool.generate(prompt, 24, tenant=tenant)
                assert len(out["tokens"]) == 24
                with lock:
                    ok[tenant] += 1
            except PoolOverloadedError as e:
                with lock:
                    shed_errs.append(e)
                time.sleep(0.2)
            except Exception as e:  # noqa: BLE001
                with lock:
                    hard_errs.append(f"{tenant}: "
                                     f"{type(e).__name__}: {e}")
                return

    threads = ([threading.Thread(target=flood, args=("bronze", k),
                                 daemon=True) for k in range(6)]
               + [threading.Thread(target=flood, args=("gold", 10 + k),
                                   daemon=True) for k in range(2)])
    try:
        for t in threads:
            t.start()
        # sustained 2x overcommit: the guardian must reach L3 and shed
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if shed_errs and pool._guardian.level == 3:
                break
            time.sleep(0.25)
        assert pool._guardian.level == 3, (
            f"ladder stuck at L{pool._guardian.level} "
            f"(sheds={len(shed_errs)})")
        assert shed_errs, "L3 without a single typed shed"
        e = shed_errs[0]
        assert e.retryable is True
        assert e.retry_after_s >= float(
            _cfg.get("overload_retry_after_min_s"))
        assert e.tenant in ("gold", "bronze")
        # escalation was monotonic: L0->L1->L2->L3, no skips
        ups = [x["to"] for x in pool._guardian.transitions]
        assert ups[:3] == ["L1", "L2", "L3"]

        # keep the flood on long enough to accumulate tenant stats
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert not hard_errs, hard_errs[0]
        # shedding favored the low-weight tenant; gold kept being
        # served and stayed inside its floor
        bronze_sheds = sum(1 for x in shed_errs if x.tenant == "bronze")
        gold_sheds = len(shed_errs) - bronze_sheds
        assert bronze_sheds >= gold_sheds, (bronze_sheds, gold_sheds)
        assert ok["gold"] >= 3, ok
        gold_p99 = pool.ttft_p99("gold")
        assert gold_p99 is not None and gold_p99 < 5.0, gold_p99

        # -- recovery: back to L0 on sustained calm, then STAYS there --
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if pool._guardian.level == 0:
                break
            time.sleep(0.25)
        assert pool._guardian.level == 0, (
            f"never recovered: L{pool._guardian.level} "
            f"{pool._guardian.transitions}")
        n_trans = len(pool._guardian.transitions)
        time.sleep(3.0)  # several tick periods of idle
        assert pool._guardian.level == 0
        assert len(pool._guardian.transitions) == n_trans, (
            "ladder flapped after recovery: "
            f"{pool._guardian.transitions[n_trans:]}")
        # full descent recorded, ending at L0
        downs = [x for x in pool._guardian.transitions
                 if x["to"] < x["from"]]
        assert len(downs) >= 3, pool._guardian.transitions

        # transitions are operator-visible as flight-recorder spans
        spans = [s for s in _fr._get().ring
                 if s.get("name") == "overload.transition"]
        assert len(spans) >= 6  # 3 up + 3 down at least
    finally:
        stop.set()
        pool.shutdown()
        _restore_overload_knobs()


def test_deadline_fast_fail_e2e(cluster):
    """Deadline-aware admission on a live pool: a request whose
    deadline cannot cover the queue's predicted drain fast-fails typed
    (no decode slot burned), a generous deadline sails through, and a
    queued request that expires is reaped typed."""
    pool = LLMPool(model_size="tiny", slots=1, max_len=96,
                   chunk_tokens=8, prompt_buckets=(16,),
                   min_replicas=1, max_replicas=1, chunk_delay_s=0.05,
                   autoscale=False)
    stop = threading.Event()

    def background(k):
        rng = np.random.RandomState(800 + k)
        while not stop.is_set():
            prompt = [int(x) for x in rng.randint(1, 250, 12)]
            try:
                pool.generate(prompt, 24)
            except Exception:  # noqa: BLE001
                return

    threads = [threading.Thread(target=background, args=(k,),
                                daemon=True) for k in range(6)]
    try:
        prompt = [1, 2, 3, 4]
        # generous deadline admits even while busy
        out = pool.generate(prompt, 8, deadline_s=120.0)
        assert len(out["tokens"]) == 8
        for t in threads:
            t.start()
        time.sleep(1.5)  # build a queue + an observed admit rate
        with pytest.raises(DeadlineExceededError) as ei:
            # 1ms can cover neither the predicted wait nor the queue:
            # fast-fail at admission or reap at expiry — typed either way
            pool.generate(prompt, 8, tenant="dl", deadline_s=0.001)
        assert ei.value.retryable is True
        assert ei.value.retry_after_s > 0
        # the tight deadline burned no decode slot and poisoned nothing:
        # the pool still serves
        out = pool.generate(prompt, 8, deadline_s=120.0)
        assert len(out["tokens"]) == 8
    finally:
        stop.set()
        pool.shutdown()


def test_ship_checkpoint_respects_bulk_squeeze(cluster, tmp_path):
    """train/checkpoint.py consults the guardian's bulk-deferral
    horizon: an engaged squeeze delays the ship (bounded), never blocks
    it, and the shipped bytes are intact."""
    from ray_tpu.serve import overload as ov
    from ray_tpu.train.checkpoint import Checkpoint, ship_checkpoint

    _cfg.set_system_config({"overload_ship_defer_max_s": 0.01})
    try:
        ov._set_bulk_deferral(True)  # horizon floor: 2s
        ck = Checkpoint.from_dict(
            {"step": 7, "blob": np.arange(1000, dtype=np.int32)},
            str(tmp_path / "squeezed"))
        t0 = time.monotonic()
        ref = ship_checkpoint(ck)
        waited = time.monotonic() - t0
        out = ray_tpu.get(ref, timeout=120)
        assert out["members"]
        # bounded: the defer budget (0.01s here) expires long before
        # the 2s horizon floor does
        assert waited < 2.0, waited
    finally:
        ov._set_bulk_deferral(False)
        _cfg.set_system_config({"overload_ship_defer_max_s": 15.0})
