"""Distributed histogram GBDT (VERDICT r4 item 5).

Reference capability: train/gbdt_trainer.py:105 via xgboost-ray's
data-parallel boosting — per-worker shard histograms, allreduce, identical
trees everywhere. The core bar: an N-worker distributed fit produces the
IDENTICAL model to the single-process fit over the same data + sharding
(the histogram merge is exact, unlike ensemble averaging)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.hist_gbdt import (
    HistParams,
    fit_distributed,
    fit_in_process,
)


def _make_data(n=1200, d=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-2, 2, size=(n, d))
    y = np.sin(X[:, 0]) + 0.5 * X[:, 1] ** 2 + 0.1 * rng.normal(size=n)
    return X, y


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 8 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_in_process_engine_learns():
    X, y = _make_data()
    shards = list(zip(np.array_split(X, 4), np.array_split(y, 4)))
    m = fit_in_process(shards, HistParams(max_depth=4), 50)
    assert m.score(X, y) > 0.9


def test_distributed_fit_matches_single_process_exactly(cluster):
    """4 histogram workers allreducing per level == the in-process
    shard-merge fit, tree for tree: predictions are bit-identical."""
    X, y = _make_data()
    shards = list(zip(np.array_split(X, 4), np.array_split(y, 4)))
    params = HistParams(max_depth=3, learning_rate=0.2)
    local = fit_in_process(shards, params, 20)
    dist = fit_distributed(shards, params, 20)
    Xq, _ = _make_data(seed=7)
    np.testing.assert_array_equal(local.raw_predict(Xq),
                                  dist.raw_predict(Xq))
    # structures too, not just outputs
    for (_, ta), (_, tb) in zip(local.trees, dist.trees):
        assert ta.feature == tb.feature
        assert ta.threshold == tb.threshold
        assert ta.value == tb.value


def test_trainer_hist_engine_end_to_end(cluster):
    """GBDTTrainer(num_workers=4): fit over Dataset shards with a valid
    set + early stopping; the predictor path is unchanged."""
    from ray_tpu import data as rdata
    from ray_tpu.train.gbdt import GBDTPredictor, GBDTTrainer

    X, y = _make_data(n=800)
    rows = [{"x0": r[0], "x1": r[1], "x2": r[2], "x3": r[3], "x4": r[4],
             "y": t} for r, t in zip(X, y)]
    train = rdata.from_items(rows[:600], parallelism=4)
    valid = rdata.from_items(rows[600:], parallelism=2)

    res = GBDTTrainer(
        datasets={"train": train, "valid": valid},
        label_column="y",
        params={"max_depth": 3, "learning_rate": 0.15},
        num_boost_round=40, rounds_per_report=10,
        early_stopping_rounds=30,
        num_workers=4,
    ).fit()
    assert res.metrics["train_score"] > 0.8, res.metrics
    assert "valid_score" in res.metrics

    pred = GBDTPredictor.from_checkpoint(res.checkpoint)
    out = pred.predict(X[:50])
    assert out.shape == (50,)
    assert np.corrcoef(out, y[:50])[0, 1] > 0.8
