"""ActorPool, Queue, and DAG tests (reference ray.util + ray.dag)."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import ActorPool, Empty, Queue


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@ray_tpu.remote(num_cpus=1)
class Doubler:
    def double(self, x):
        return 2 * x


def test_actor_pool_map_ordered(cluster):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert out == [2 * i for i in range(8)]


def test_actor_pool_unordered(cluster):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map_unordered(lambda a, v: a.double.remote(v), range(6)))
    assert sorted(out) == [2 * i for i in range(6)]


def test_queue_roundtrip(cluster):
    q = Queue(maxsize=4)
    for i in range(4):
        q.put(i)
    assert q.full()
    assert [q.get() for _ in range(4)] == [0, 1, 2, 3]
    assert q.empty()
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_queue_producer_consumer(cluster):
    q = Queue()

    @ray_tpu.remote(num_cpus=1)
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    ref = producer.remote(q, 10)
    got = [q.get(timeout=30) for _ in range(10)]
    assert got == list(range(10))
    assert ray_tpu.get(ref, timeout=30)


def test_dag_bind_execute(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    from ray_tpu.dag import InputNode

    x = InputNode(0)
    s = add.bind(x, 10)
    graph = mul.bind(s, s)  # shared node executes once
    ref = graph.execute(5)
    assert ray_tpu.get(ref, timeout=60) == 225  # (5+10)^2


def test_multiprocessing_pool_shim(cluster):
    from ray_tpu.util.multiprocessing import Pool

    def sq(x):
        return x * x

    def add(a, b):
        return a + b

    with Pool(processes=4) as p:
        assert p.map(sq, range(6)) == [0, 1, 4, 9, 16, 25]
        assert p.starmap(add, [(1, 2), (3, 4)]) == [3, 7]
        assert p.apply(add, (20, 22)) == 42
        r = p.map_async(sq, [2, 3])
        assert r.get(timeout=60) == [4, 9]
        assert list(p.imap(sq, [5])) == [25]
    # closed pool rejects work (stdlib semantics)
    with pytest.raises(ValueError, match="not running"):
        p.map(sq, [1])


def test_multiprocessing_pool_initializer_and_lazy_imap(cluster):
    from ray_tpu.util.multiprocessing import Pool

    def init_env(tag):
        import os as _os

        _os.environ["POOL_TAG"] = tag

    def read_tag(_):
        import os as _os

        return _os.environ.get("POOL_TAG")

    with Pool(processes=2, initializer=init_env,
              initargs=("hello",)) as p:
        assert p.map(read_tag, range(3)) == ["hello"] * 3

        # lazy imap: pulls from the generator incrementally
        pulled = []

        def gen():
            for i in range(6):
                pulled.append(i)
                yield i

        out_iter = p.imap(lambda x: x + 1, gen())
        first = next(out_iter)
        assert first == 1
        assert len(pulled) <= 4  # window of `processes`+1, not all 6
        assert list(out_iter) == [2, 3, 4, 5, 6]
