"""ActorPool, Queue, and DAG tests (reference ray.util + ray.dag)."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import ActorPool, Empty, Queue


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@ray_tpu.remote(num_cpus=1)
class Doubler:
    def double(self, x):
        return 2 * x


def test_actor_pool_map_ordered(cluster):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert out == [2 * i for i in range(8)]


def test_actor_pool_unordered(cluster):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map_unordered(lambda a, v: a.double.remote(v), range(6)))
    assert sorted(out) == [2 * i for i in range(6)]


def test_queue_roundtrip(cluster):
    q = Queue(maxsize=4)
    for i in range(4):
        q.put(i)
    assert q.full()
    assert [q.get() for _ in range(4)] == [0, 1, 2, 3]
    assert q.empty()
    with pytest.raises(Empty):
        q.get_nowait()
    q.shutdown()


def test_queue_producer_consumer(cluster):
    q = Queue()

    @ray_tpu.remote(num_cpus=1)
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    ref = producer.remote(q, 10)
    got = [q.get(timeout=30) for _ in range(10)]
    assert got == list(range(10))
    assert ray_tpu.get(ref, timeout=30)


def test_dag_bind_execute(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    from ray_tpu.dag import InputNode

    x = InputNode(0)
    s = add.bind(x, 10)
    graph = mul.bind(s, s)  # shared node executes once
    ref = graph.execute(5)
    assert ray_tpu.get(ref, timeout=60) == 225  # (5+10)^2
