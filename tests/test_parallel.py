"""Sharding tests on the 8-device virtual CPU mesh: every parallelism layout
compiles, runs, and produces results identical to single-device execution."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import llama
from ray_tpu.parallel import (
    DEFAULT_RULES,
    MeshConfig,
    build_mesh,
    logical_to_mesh_spec,
    logical_tree_to_shardings,
    use_mesh,
)
from ray_tpu.train import batch_sharding, init_train_state, make_train_step


def test_logical_to_mesh_spec_dedup():
    spec = logical_to_mesh_spec(("batch", "seq", "embed"))
    # fsdp already consumed by batch -> embed falls back to replicated, and
    # the trailing None is trimmed.
    assert spec[0] == ("dp", "fsdp")
    assert spec[1] == "sp"
    assert len(spec) == 2


MESHES = [
    MeshConfig(dp=8),
    MeshConfig(fsdp=8),
    MeshConfig(fsdp=2, sp=2, tp=2),
    MeshConfig(dp=2, fsdp=2, tp=2),
    MeshConfig(fsdp=4, tp=2),
]


@pytest.mark.parametrize("mcfg", MESHES, ids=lambda m: m.describe())
def test_train_step_all_layouts(devices8, mcfg, rng):
    """One train step under each mesh layout matches the single-device result."""
    cfg = llama.LlamaConfig.tiny(n_layers=2)
    mesh = build_mesh(mcfg, devices8)
    opt = optax.adam(1e-3)

    toks = jax.random.randint(jax.random.PRNGKey(7), (8, 33), 0, cfg.vocab_size)
    # inputs/targets form: seq length 32 divides the sp axis.
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    # Single-device truth.
    params0 = llama.init_params(cfg, rng)
    (loss0, _), grads0 = jax.value_and_grad(llama.loss_fn, has_aux=True)(
        params0, batch, cfg
    )

    state, state_sh = init_train_state(
        lambda k: llama.init_params(cfg, k),
        llama.param_logical_axes(cfg),
        opt,
        mesh,
        key=rng,
    )
    step = make_train_step(
        lambda p, b: llama.loss_fn(p, b, cfg), opt, mesh, state_sh,
        donate_state=False,
    )
    with use_mesh(mesh):
        sharded_batch = jax.device_put(batch, batch_sharding(mesh))
        state2, metrics = step(state, sharded_batch)

    np.testing.assert_allclose(float(metrics["loss"]), float(loss0), rtol=2e-4)
    assert int(jax.device_get(state2.step)) == 1

    # Params actually sharded: under pure fsdp the wq leaf shard is 1/8 size.
    if mcfg.fsdp == 8:
        wq = state2.params["layers"]["wq"]
        shard = wq.addressable_shards[0].data
        assert shard.shape[1] == wq.shape[1] // 8


def test_opt_state_shardings_follow_param_paths(devices8, rng):
    """Adam moments for wo (shape == wq's) must use wo's transposed sharding."""
    cfg = llama.LlamaConfig.tiny(n_heads=4, n_kv_heads=4)  # hq*hd == d_model
    mesh = build_mesh(MeshConfig(fsdp=4, tp=2), devices8)
    opt = optax.adam(1e-3)
    state, state_sh = init_train_state(
        lambda k: llama.init_params(cfg, k),
        llama.param_logical_axes(cfg),
        opt,
        mesh,
        key=rng,
    )
    mu = state.opt_state[0].mu["layers"]
    # wq: (layers, embed->fsdp, heads->tp); wo: (layers, heads->tp, embed->fsdp)
    assert mu["wq"].sharding.spec == state.params["layers"]["wq"].sharding.spec
    assert mu["wo"].sharding.spec == state.params["layers"]["wo"].sharding.spec
    assert (
        state.params["layers"]["wq"].sharding.spec
        != state.params["layers"]["wo"].sharding.spec
    )


def test_param_shardings_cover_tree(devices8, rng):
    cfg = llama.LlamaConfig.tiny()
    mesh = build_mesh(MeshConfig(fsdp=4, tp=2), devices8)
    sh = logical_tree_to_shardings(llama.param_logical_axes(cfg), mesh, DEFAULT_RULES)
    params = llama.init_params(cfg, rng)
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(sh)
