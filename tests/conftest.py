"""Test fixture backbone: an 8-device virtual CPU mesh.

Analog of the reference's fake-cluster test backbone
(reference: python/ray/cluster_utils.py:99 `Cluster`, conftest fixtures
python/ray/tests/conftest.py:359) — multi-"chip" semantics without TPU
hardware, via XLA host-platform virtual devices.

Must set env vars before jax initializes its backends, hence the top-of-file
placement and the sys.modules guard.
"""

import os

# jax may already be imported (pytest plugins) with its config snapshotted from
# the env, so set both the env var and the live config; backends init lazily.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

assert jax.default_backend() == "cpu", (
    "jax backend initialized before conftest could force CPU; "
    f"got {jax.default_backend()}"
)


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)
