"""Test fixture backbone: an 8-device virtual CPU mesh.

Analog of the reference's fake-cluster test backbone
(reference: python/ray/cluster_utils.py:99 `Cluster`, conftest fixtures
python/ray/tests/conftest.py:359) — multi-"chip" semantics without TPU
hardware, via XLA host-platform virtual devices.

Must set env vars before jax initializes its backends, hence the top-of-file
placement and the sys.modules guard.
"""

import os

# jax may already be imported (pytest plugins) with its config snapshotted from
# the env, so set both the env var and the live config; backends init lazily.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5) has no jax_num_cpu_devices; the
    # xla_force_host_platform_device_count XLA flag above covers it
    pass

assert jax.default_backend() == "cpu", (
    "jax backend initialized before conftest could force CPU; "
    f"got {jax.default_backend()}"
)


def pytest_configure(config):
    # tier-1 runs `-m "not slow"`; register the marker so long soaks
    # (test_chaos_soak.py) opt out without unknown-mark warnings
    config.addinivalue_line(
        "markers",
        "slow: long-running soak/perf tests excluded from the tier-1 run",
    )


@pytest.fixture(autouse=True)
def _hang_watchdog():
    """Convert silent suite wedges into diagnosed failures: if any single
    test runs >10min, faulthandler dumps EVERY thread's stack and the
    process exits — a monolithic `pytest tests/` run must never sit
    stalled for an hour with idle leaked workers (observed in r4: a
    cross-file hang wedged the suite >44min with zero output).

    The dump goes to a FILE (ray_tpu_hang_dump.log under the system
    temp dir), not stderr: pytest's default fd-level capture dup2s
    fd 2 before this conftest even imports, so both sys.stderr and
    sys.__stderr__ land in the doomed process's capture temp file —
    exactly what made the first watchdog firing an undiagnosable
    silent rc=1. A plain file survives the hard _exit."""
    import faulthandler

    faulthandler.dump_traceback_later(600, exit=True,
                                      file=_watchdog_log())
    yield
    faulthandler.cancel_dump_traceback_later()


_WATCHDOG_FH = None


def _watchdog_log():
    global _WATCHDOG_FH
    if _WATCHDOG_FH is None:
        import tempfile

        path = os.path.join(tempfile.gettempdir(),
                            "ray_tpu_hang_dump.log")
        _WATCHDOG_FH = open(path, "a")  # noqa: SIM115 — must outlive tests
        print(f"[conftest] hang-watchdog dumps -> {path}")
    return _WATCHDOG_FH


def _kill_orphan_workers():
    """Reap ray_tpu worker processes that outlived their cluster: ones
    reparented to init (their spawning agent/head died) or still parented
    to this pytest process after module teardown. Leaked workers hold
    ports/sockets and wedge later modules' clusters."""
    import signal

    me = os.getpid()
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit():
            continue
        pid = int(pid_s)
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ")
            if b"ray_tpu.core.worker_proc" not in cmd:
                continue
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split()[3])
            if ppid in (me, 1):
                os.kill(pid, signal.SIGKILL)
        except (OSError, ValueError, IndexError):
            continue


@pytest.fixture(scope="module", autouse=True)
def _reap_leaked_workers():
    """Cross-file process hygiene (instantiated before, finalized after,
    every module-scoped cluster fixture)."""
    yield
    _kill_orphan_workers()


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture
def rng():
    return jax.random.PRNGKey(0)
