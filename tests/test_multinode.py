"""Multi-node scheduling/objects/failure tests on the fake cluster.

Mirrors reference python/ray/tests/ multi-node suites (test_multi_node*.py,
test_object_reconstruction.py scope, chaos NodeKiller pattern) using
cluster_utils.Cluster with several in-process node agents.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster3():
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_spillback_uses_other_nodes(cluster3):
    @ray_tpu.remote(num_cpus=2)
    def node_store():
        import os

        return os.environ["RAY_TPU_NODE_ID"]

    # 3 concurrent 2-CPU tasks can only run by using all three nodes
    refs = [node_store.remote() for _ in range(3)]
    nodes = set(ray_tpu.get(refs, timeout=120))
    assert len(nodes) >= 2  # spilled beyond the head node


def test_object_transfer_between_nodes(cluster3):
    @ray_tpu.remote(num_cpus=2)
    def produce():
        return np.arange(400_000, dtype=np.float32)

    @ray_tpu.remote(num_cpus=2)
    def consume(arr):
        return float(arr.sum())

    # force producer and consumer onto different nodes via resource pressure
    ref = produce.remote()
    outs = [consume.remote(ref) for _ in range(3)]
    expected = float(np.arange(400_000, dtype=np.float32).sum())
    assert all(v == expected for v in ray_tpu.get(outs, timeout=120))


def test_placement_group_spread(cluster3):
    pg = ray_tpu.placement_group(
        [{"CPU": 1}, {"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD"
    )
    assert pg.ready(timeout=30)
    assert len(set(pg.bundle_nodes)) == 3
    ray_tpu.remove_placement_group(pg)


def test_placement_group_pack(cluster3):
    pg = ray_tpu.placement_group(
        [{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK"
    )
    assert pg.ready(timeout=30)
    assert len(set(pg.bundle_nodes)) == 1
    ray_tpu.remove_placement_group(pg)


def test_node_death_actor_restarts_elsewhere(cluster3):
    victim = cluster3.agents[-1]

    @ray_tpu.remote(num_cpus=2)
    class Pinned:
        def node(self):
            import os

            return os.environ["RAY_TPU_NODE_ID"]

    actors = [Pinned.options(max_restarts=3).remote() for _ in range(3)]
    homes = ray_tpu.get([a.node.remote() for a in actors], timeout=120)
    target_hex = victim.node_id.hex()
    victims = [a for a, h in zip(actors, homes) if h == target_hex]
    if not victims:
        pytest.skip("no actor landed on victim node")
    # chaos: kill the node (reference NodeKillerActor analog)
    cluster3.remove_node(victim)
    a = victims[0]
    deadline = time.time() + 60
    new_home = None
    while time.time() < deadline:
        try:
            new_home = ray_tpu.get(a.node.remote(), timeout=15)
            break
        except (ray_tpu.RayActorError, ray_tpu.GetTimeoutError):
            time.sleep(0.3)
    assert new_home is not None and new_home != target_hex


def test_node_death_task_retries(cluster3):
    @ray_tpu.remote(num_cpus=2, max_retries=5)
    def slow_id():
        import os
        import time as _t

        _t.sleep(1.5)
        return os.environ["RAY_TPU_NODE_ID"]

    refs = [slow_id.remote() for _ in range(3)]
    time.sleep(0.5)  # let tasks spread + start
    cluster3.remove_node(cluster3.agents[-1])
    got = ray_tpu.get(refs, timeout=120)
    assert len(got) == 3  # all completed despite the node loss
