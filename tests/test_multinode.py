"""Multi-node scheduling/objects/failure tests on the fake cluster.

Mirrors reference python/ray/tests/ multi-node suites (test_multi_node*.py,
test_object_reconstruction.py scope, chaos NodeKiller pattern) using
cluster_utils.Cluster with several in-process node agents.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster3():
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_spillback_uses_other_nodes(cluster3):
    @ray_tpu.remote(num_cpus=2)
    def node_store():
        import os
        import time as _t

        _t.sleep(1.0)  # hold the cpus so the three tasks truly overlap
        return os.environ["RAY_TPU_NODE_ID"]

    # 3 concurrent 2-CPU tasks can only run by using all three nodes
    # (without the sleep, fast completions let the worker-lease fast path
    # legitimately serialize them on one node)
    refs = [node_store.remote() for _ in range(3)]
    nodes = set(ray_tpu.get(refs, timeout=120))
    assert len(nodes) >= 2  # spilled beyond the head node


def test_object_transfer_between_nodes(cluster3):
    @ray_tpu.remote(num_cpus=2)
    def produce():
        return np.arange(400_000, dtype=np.float32)

    @ray_tpu.remote(num_cpus=2)
    def consume(arr):
        return float(arr.sum())

    # force producer and consumer onto different nodes via resource pressure
    ref = produce.remote()
    outs = [consume.remote(ref) for _ in range(3)]
    expected = float(np.arange(400_000, dtype=np.float32).sum())
    assert all(v == expected for v in ray_tpu.get(outs, timeout=120))


def test_placement_group_spread(cluster3):
    pg = ray_tpu.placement_group(
        [{"CPU": 1}, {"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD"
    )
    assert pg.ready(timeout=30)
    assert len(set(pg.bundle_nodes)) == 3
    ray_tpu.remove_placement_group(pg)


def test_placement_group_pack(cluster3):
    pg = ray_tpu.placement_group(
        [{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK"
    )
    assert pg.ready(timeout=30)
    assert len(set(pg.bundle_nodes)) == 1
    ray_tpu.remove_placement_group(pg)


@pytest.mark.slow  # ~16s; node-death task-retry/queued-fail/chaos-kill tests keep tier-1 coverage
def test_node_death_actor_restarts_elsewhere(cluster3):
    # 1-CPU actors on 2-CPU nodes: after a node dies, the survivors still
    # have spare capacity so the restart is actually placeable.
    @ray_tpu.remote(num_cpus=1)
    class Pinned:
        def node(self):
            import os

            return os.environ["RAY_TPU_NODE_ID"]

    actors = [Pinned.options(max_restarts=3).remote() for _ in range(3)]
    homes = ray_tpu.get([a.node.remote() for a in actors], timeout=120)
    # DETERMINISTIC victim choice: kill whichever NON-HEAD node actually
    # hosts an actor (the old fixed-victim version silently skipped on a
    # lucky placement — a chaos assertion that can vanish isn't one)
    head_hex = cluster3.head_agent.node_id.hex()
    by_home = {h: a for a, h in zip(actors, homes) if h != head_hex}
    assert by_home, f"all actors landed on the head node: {homes}"
    target_hex, a = next(iter(by_home.items()))
    victim = next(ag for ag in cluster3.agents
                  if ag.node_id.hex() == target_hex)
    # chaos: kill the node (reference NodeKillerActor analog)
    cluster3.remove_node(victim)
    deadline = time.time() + 60
    new_home = None
    while time.time() < deadline:
        try:
            new_home = ray_tpu.get(a.node.remote(), timeout=15)
            break
        except (ray_tpu.RayActorError, ray_tpu.GetTimeoutError):
            time.sleep(0.3)
    assert new_home is not None and new_home != target_hex


def test_node_death_task_retries(cluster3):
    @ray_tpu.remote(num_cpus=2, max_retries=5)
    def slow_id():
        import os
        import time as _t

        _t.sleep(1.5)
        return os.environ["RAY_TPU_NODE_ID"]

    refs = [slow_id.remote() for _ in range(3)]
    time.sleep(0.5)  # let tasks spread + start
    cluster3.remove_node(cluster3.agents[-1])
    got = ray_tpu.get(refs, timeout=120)
    assert len(got) == 3  # all completed despite the node loss


def test_pg_actor_uses_bundle_resources(cluster3):
    """An actor whose bundle reserves the whole node must still schedule:
    PG actors draw from the committed bundle, not the depleted node pool
    (advisor round-1 high finding)."""
    pg = ray_tpu.placement_group([{"CPU": 2}], strategy="STRICT_PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote(num_cpus=2)
    class Big:
        def ping(self):
            return "pong"

    a = Big.options(
        placement_group=pg, placement_group_bundle_index=0
    ).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ray_tpu.kill(a)
    ray_tpu.remove_placement_group(pg)


def test_node_death_fails_queued_tasks(cluster3):
    """Tasks queued/running on a dying node are failed/retried via the
    owner's task_located + node_dead tracking, not lost until timeout
    (advisor round-1 high finding)."""

    victim = cluster3.agents[-1]

    @ray_tpu.remote(num_cpus=1, max_retries=0)
    def stuck():
        import time as _t

        _t.sleep(300)  # far longer than the test; must be failed, not joined
        return "done"

    # Pin both a running and a queued task onto the victim node.
    pin = {"node_id": victim.node_id}
    refs = [stuck.options(scheduling_strategy=pin).remote()
            for _ in range(3)]
    time.sleep(1.0)  # let tasks land on the agent
    cluster3.remove_node(victim)
    # Every pinned ref must fail fast with the node-death reason; none may
    # take the full sleep.
    for r in refs:
        with pytest.raises(ray_tpu.RayTaskError, match="node died"):
            ray_tpu.get(r, timeout=30)


def test_chaos_random_node_kill(cluster3):
    """NodeKiller-style chaos (reference test_utils.py:1367): kill a random
    non-head agent under task+actor load; cluster must stay usable."""
    import random

    @ray_tpu.remote(num_cpus=1, max_retries=5)
    def work(i):
        import time as _t

        _t.sleep(0.1)
        return i

    @ray_tpu.remote(num_cpus=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.options(max_restarts=5).remote()
    refs = [work.remote(i) for i in range(12)]
    # non-head victims only (per the docstring): killing the head agent
    # kills the driver's own store/agent — that's driver death, a
    # different failure mode than node chaos
    victim = random.choice(
        [a for a in cluster3.agents if a is not cluster3.head_agent]
    )
    cluster3.remove_node(victim)
    # tasks with retries finish; the cluster still schedules new work
    # (generous budget: under the FULL suite this box runs dozens of
    # worker subprocesses and retry chains stretch accordingly)
    got = ray_tpu.get(refs, timeout=240)
    assert sorted(got) == list(range(12))
    # the counter may be mid-restart if its node was the victim: retry
    deadline = time.time() + 90
    bumped = None
    while time.time() < deadline:
        try:
            bumped = ray_tpu.get(c.bump.remote(), timeout=20)
            break
        except (ray_tpu.RayActorError, ray_tpu.GetTimeoutError):
            time.sleep(0.5)
    assert bumped is not None and bumped >= 1
    more = ray_tpu.get([work.remote(i) for i in range(5)], timeout=120)
    assert sorted(more) == list(range(5))


def test_locality_aware_scheduling(cluster3):
    """A task consuming a big object runs on the node that holds it
    (reference lease_policy.h locality-aware leasing)."""
    victim_free = cluster3.agents[-1]
    pin = {"node_id": victim_free.node_id}

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def produce():
        return np.ones(2_000_000, dtype=np.float64)  # 16 MB

    @ray_tpu.remote(num_cpus=1)
    def where_am_i(arr):
        import os

        return os.environ["RAY_TPU_NODE_ID"], float(arr[0])

    ref = produce.options(scheduling_strategy=pin).remote()
    ray_tpu.wait([ref], timeout=60)
    # submit several consumers with no placement hints: locality should
    # put them on the producer's node rather than the submitter's
    outs = ray_tpu.get(
        [where_am_i.remote(ref) for _ in range(3)], timeout=120
    )
    nodes = {n for n, _ in outs}
    assert victim_free.node_id.hex() in nodes
    assert all(v == 1.0 for _, v in outs)


def test_tpu_slice_gang_placement():
    """TPU-first scheduling: a STRICT_PACK gang over TPU chips + the
    tpu-slice topology resource lands on the one node exposing that slice
    (SURVEY §7: PG bundles map to ICI sub-meshes)."""
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    tpu_node = c.add_node(resources={
        "CPU": 4, "memory": 2 * 2**30, "TPU": 4, "tpu-slice:v5e-4": 1,
    })
    # decoy: same chip count, DIFFERENT slice topology — the slice
    # resource in bundle 0 must discriminate between them
    c.add_node(resources={
        "CPU": 4, "memory": 2 * 2**30, "TPU": 4, "tpu-slice:v5e-8": 1,
    })
    c.connect()
    try:
        pg = ray_tpu.placement_group(
            [{"TPU": 2, "CPU": 1, "tpu-slice:v5e-4": 1},
             {"TPU": 2, "CPU": 1}],
            strategy="STRICT_PACK",
        )
        assert pg.ready(timeout=30)
        assert set(pg.bundle_nodes) == {tpu_node.node_id}

        @ray_tpu.remote(num_cpus=1, num_tpus=2)
        def where():
            import os

            return os.environ["RAY_TPU_NODE_ID"]

        homes = ray_tpu.get(
            [where.options(placement_group=pg,
                           placement_group_bundle_index=i).remote()
             for i in range(2)],
            timeout=120,
        )
        assert all(h == tpu_node.node_id.hex() for h in homes)
        ray_tpu.remove_placement_group(pg)
    finally:
        c.shutdown()


def test_delta_heartbeat_payload_shrinks_when_idle():
    """Delta resource sync (reference ray_syncer.h:86): once a node's
    state stops changing, its heartbeat carries only its id and the
    cluster-view reply carries no nodes — >10x smaller on the wire than
    the full snapshot protocol."""
    from ray_tpu._private.rpc import pack

    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    for _ in range(4):
        c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    try:
        agent = c.head_agent
        # let a few beats flow so _hb_sent converges
        deadline = time.time() + 15
        while time.time() < deadline:
            time.sleep(1.0)
            delta = agent._build_heartbeat()
            if set(delta) == {"node_id"}:
                break
        full = {"node_id": agent.node_id, **agent._hb_snapshot()}
        assert set(delta) == {"node_id"}, delta.keys()
        assert len(pack(full)) > 5 * len(pack(delta)), (
            len(pack(full)), len(pack(delta)))

        # view delta: an idle 5-node cluster ships ZERO node dicts
        cp = c.cp
        full_view = c.io.run(cp.rpc_get_cluster_view(None, {}))
        assert len(full_view["nodes"]) == 5
        delta_view = c.io.run(cp.rpc_get_cluster_view(
            None, {"since": full_view["ver"]}))
        assert delta_view["nodes"] == []
        # the per-beat PROTOCOL (heartbeat up + view down) drops >10x
        full_bytes = len(pack(full)) + len(pack(full_view))
        delta_bytes = len(pack(delta)) + len(pack(delta_view))
        assert full_bytes > 10 * delta_bytes, (full_bytes, delta_bytes)

        # a change on one node ships exactly that node
        agent2 = c.agents[1]
        c.io.run(cp.rpc_heartbeat(None, {
            "node_id": agent2.node_id, "queued": 7}))
        after = c.io.run(cp.rpc_get_cluster_view(
            None, {"since": full_view["ver"]}))
        assert [n["node_id"] for n in after["nodes"]] == [agent2.node_id]
        assert after["nodes"][0]["queued"] == 7
    finally:
        c.shutdown()
