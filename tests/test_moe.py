"""MoE capacity routing: parity vs the dense-dispatch oracle, token
dropping, expert-parallel FLOPs reduction, and end-to-end training.

VERDICT r2 item 4 'done' bar. Design-new (the reference has no MoE,
SURVEY §2.7); the public pattern anchor is GShard/Switch dispatch einsums.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama


def _cfg(**kw):
    base = dict(
        vocab_size=128, d_model=64, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=128, max_seq_len=32, n_experts=4, top_k=2, dtype="float32",
        remat=False, use_flash=False,
    )
    base.update(kw)
    return llama.LlamaConfig(**base)


def _mlp_params(cfg, key):
    p = llama.init_params(cfg, key)
    layer0 = jax.tree_util.tree_map(lambda a: a[0], p["layers"])
    return layer0


def test_capacity_matches_dense_when_nothing_drops():
    """With capacity >= T*top_k no token can drop, so capacity routing
    computes EXACTLY the dense-dispatch weighted sum."""
    cfg_d = _cfg(moe_impl="dense")
    cfg_c = _cfg(moe_impl="capacity", capacity_factor=float(cfg_d.n_experts))
    p = _mlp_params(cfg_d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 64), jnp.float32)
    y_dense = llama._moe_mlp(cfg_d, p, x)
    y_cap = llama._moe_mlp(cfg_c, p, x)
    np.testing.assert_allclose(np.asarray(y_cap), np.asarray(y_dense),
                               atol=2e-5, rtol=1e-4)


def test_capacity_matches_dense_on_8dev_mesh():
    """Same parity under a dp x ep mesh: the dispatch einsums must be
    sharding-correct (E over ep, B over dp)."""
    from ray_tpu.parallel import MeshConfig, build_mesh, use_mesh
    from ray_tpu.parallel.sharding import logical_to_mesh_spec, DEFAULT_RULES
    from jax.sharding import NamedSharding

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = build_mesh(MeshConfig(dp=2, ep=4), devs[:8])
    cfg_d = _cfg(moe_impl="dense")
    cfg_c = _cfg(moe_impl="capacity", capacity_factor=float(cfg_d.n_experts))
    p = _mlp_params(cfg_d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 64), jnp.float32)
    with use_mesh(mesh):
        x_sh = jax.device_put(x, NamedSharding(mesh, logical_to_mesh_spec(
            ("batch", "seq", "embed"), DEFAULT_RULES, mesh)))
        y_dense = jax.jit(lambda p_, x_: llama._moe_mlp(cfg_d, p_, x_))(p, x_sh)
        y_cap = jax.jit(lambda p_, x_: llama._moe_mlp(cfg_c, p_, x_))(p, x_sh)
    np.testing.assert_allclose(np.asarray(y_cap), np.asarray(y_dense),
                               atol=2e-5, rtol=1e-4)


def test_tokens_drop_at_low_capacity():
    """capacity_factor << 1 forces drops: dropped tokens contribute zero
    (residual carries them), and outputs differ from dense."""
    cfg_c = _cfg(moe_impl="capacity", capacity_factor=0.25)
    cfg_d = _cfg(moe_impl="dense")
    p = _mlp_params(cfg_d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 64), jnp.float32)
    y_cap = llama._moe_mlp(cfg_c, p, x)
    y_dense = llama._moe_mlp(cfg_d, p, x)
    assert not np.allclose(np.asarray(y_cap), np.asarray(y_dense), atol=1e-3)
    # every output row is finite (drops zero cleanly, no NaNs from the
    # one-hot arithmetic)
    assert np.isfinite(np.asarray(y_cap)).all()


def test_expert_flops_scale_down():
    """Per-step MLP FLOPs: capacity routing at E=4/top2/cf=1.0 must cost
    ~top_k*cf/E = half the dense-dispatch expert FLOPs."""
    cfg_d = _cfg(moe_impl="dense")
    cfg_c = _cfg(moe_impl="capacity", capacity_factor=1.0)
    p = _mlp_params(cfg_d, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 64), jnp.float32)

    def flops(cfg):
        f = jax.jit(lambda p_, x_: llama._moe_mlp(cfg, p_, x_))
        c = f.lower(p, x).compile().cost_analysis()
        c = c[0] if isinstance(c, (list, tuple)) else c
        return c["flops"]

    fd, fc = flops(cfg_d), flops(cfg_c)
    # dispatch/combine one-hot einsums add overhead, but the expert
    # matmuls dominate; expect a clear win, not exactly 2x
    assert fc < 0.75 * fd, f"capacity flops {fc} vs dense {fd}"


@pytest.mark.slow  # ~16s train loop; capacity/drop/flops units above are tier-1
def test_moe_tiny_trains():
    """moe-tiny end-to-end: loss decreases with the capacity impl and
    tracks the dense impl's trajectory."""
    import optax

    from ray_tpu.parallel import MeshConfig, build_mesh, use_mesh
    from ray_tpu.train import (batch_sharding, init_train_state,
                               make_train_step)

    losses = {}
    for impl in ("dense", "capacity"):
        cfg = llama.llama2_size("moe-tiny")
        cfg = llama.LlamaConfig(**{
            **cfg.__dict__, "moe_impl": impl, "capacity_factor": 2.0,
            "remat": False, "use_flash": False, "max_seq_len": 32,
        })
        mesh = build_mesh(MeshConfig(), jax.devices()[:1])
        opt = optax.adamw(3e-3)
        state, sh = init_train_state(
            lambda k: llama.init_params(cfg, k),
            llama.param_logical_axes(cfg), opt, mesh,
            key=jax.random.PRNGKey(0))
        step = make_train_step(
            lambda p, b: llama.loss_fn(p, b, cfg), opt, mesh, sh)
        toks = jax.random.randint(jax.random.PRNGKey(1), (4, 33), 0,
                                  cfg.vocab_size, dtype=jnp.int32)
        data = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        with use_mesh(mesh):
            data = jax.device_put(data, batch_sharding(mesh))
            ls = []
            for _ in range(8):
                state, m = step(state, data)
                ls.append(float(m["loss"]))
        losses[impl] = ls
        assert ls[-1] < ls[0] * 0.9, f"{impl}: loss did not decrease {ls}"
    # same init, generous capacity: trajectories should be close
    np.testing.assert_allclose(losses["capacity"][-1], losses["dense"][-1],
                               rtol=0.15)
