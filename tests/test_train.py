"""JaxTrainer end-to-end: gang scheduling, jax.distributed mesh spanning
worker processes, session.report streaming, sharded checkpointing, and
gang restart after a killed worker.

Reference test analog: python/ray/train/tests/test_data_parallel_trainer.py
+ test_backend_executor.py fault cases. Worker processes are genuinely
separate (spawned by node agents); each contributes 2 virtual CPU devices
to one global jax.distributed mesh — the CPU stand-in for multi-host TPU.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import (
    Checkpoint,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    restore_state,
    save_state,
)

NUM_WORKERS = 2
DEV_PER_WORKER = 2


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.add_node(resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _train_loop(config):
    """Runs identically on every worker (single program, multi process)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import llama
    from ray_tpu.parallel import MeshConfig, build_mesh, use_mesh
    from ray_tpu.train import (
        batch_sharding,
        init_train_state,
        make_train_step,
        restore_state,
        save_state,
        session,
    )

    cfg = llama.LlamaConfig.tiny()
    world_devices = jax.device_count()
    mesh = build_mesh(MeshConfig(dp=world_devices), jax.devices())
    opt = optax.adam(1e-2)

    with use_mesh(mesh):
        state, state_sh = init_train_state(
            lambda k: llama.init_params(cfg, k),
            llama.param_logical_axes(cfg),
            opt,
            mesh,
            key=jax.random.PRNGKey(0),
        )
        start_step = 0
        ckpt = session.get_checkpoint()
        if ckpt is not None:
            state = restore_state(ckpt.path, shardings=state_sh)
            start_step = ckpt.to_dict()["step"]

        step_fn = make_train_step(
            lambda p, b: llama.loss_fn(p, b, cfg), opt, mesh, state_sh
        )

        batch_sh = batch_sharding(mesh)
        rng = np.random.RandomState(0)
        full = rng.randint(0, cfg.vocab_size, size=(8, 33), dtype=np.int64)

        def _global(arr):
            return jax.make_array_from_callback(
                arr.shape, batch_sh, lambda idx: arr[idx]
            )

        data = {"tokens": _global(full)}
        for step_i in range(start_step, config["steps"]):
            state, metrics = step_fn(state, data)
            loss = float(jax.device_get(metrics["loss"]))
            ckpt_dir = os.path.join(
                config["storage"], f"step_{step_i:04d}"
            )
            ckpt = save_state(
                state, ckpt_dir, extra={"step": step_i + 1, "loss": loss}
            )
            if config.get("die_at") is not None and \
                    step_i == config["die_at"] and \
                    session.get_checkpoint() is None:
                # first incarnation only: hard-kill this worker process
                if session.get_world_rank() == 1:
                    os._exit(1)
                else:
                    time.sleep(30)  # peers stall; driver sees the dead actor
            session.report({"loss": loss, "step": step_i + 1},
                           checkpoint=ckpt if
                           session.get_world_rank() == 0 else None)


def _scaling():
    return ScalingConfig(
        num_workers=NUM_WORKERS,
        resources_per_worker={"CPU": 1},
        devices_per_worker=DEV_PER_WORKER,
        platform="cpu",
        placement_strategy="SPREAD",
    )


def test_trainer_runs_to_completion(cluster, tmp_path):
    trainer = JaxTrainer(
        _train_loop,
        train_loop_config={"steps": 5, "storage": str(tmp_path)},
        scaling_config=_scaling(),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert len(result.metrics_history) == 5
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]  # tiny llama learns the fixed batch
    assert result.checkpoint is not None
    assert result.metrics["step"] == 5


def test_trainer_restarts_after_worker_death(cluster, tmp_path):
    trainer = JaxTrainer(
        _train_loop,
        train_loop_config={
            "steps": 6, "storage": str(tmp_path), "die_at": 2,
        },
        scaling_config=_scaling(),
        run_config=RunConfig(name="t2", storage_path=str(tmp_path),
                             max_failures=1),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    # resumed from the step-2 checkpoint and completed all 6 steps
    assert result.metrics["step"] == 6
    steps = [m["step"] for m in result.metrics_history]
    assert steps[-1] == 6
    # loss kept decreasing across the restart boundary
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]


def test_checkpoint_roundtrip_sharded(cluster, tmp_path):
    """save_state/restore_state on a single-process 8-device mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel import MeshConfig, build_mesh

    mesh = build_mesh(MeshConfig(fsdp=4, tp=2), jax.devices()[:8])
    sh = NamedSharding(mesh, P("fsdp", "tp"))
    x = jax.device_put(jnp.arange(64.0).reshape(8, 8), sh)
    state = {"w": x, "step": 3}
    path = str(tmp_path / "ck")
    save_state(state, path, extra={"tag": "hi"})
    got = restore_state(path, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got["w"]), np.asarray(x))
    assert got["step"] == 3
    assert Checkpoint(path).to_dict() == {"tag": "hi"}


def test_trainer_consumes_streaming_dataset(cluster, tmp_path):
    """Data->Train integration (VERDICT round-1 item 6 'done' bar): each
    train worker consumes its own streaming_split shard."""
    from ray_tpu import data as rdata

    ds = rdata.range(64, parallelism=4, block_size=8)
    shards = ds.streaming_split(NUM_WORKERS)

    def data_loop(config):
        import json
        import os as _os

        import numpy as np

        from ray_tpu.train import session

        rank = session.get_world_rank()
        it = config["shards"][rank]
        total = 0
        seen = []
        for block in it:
            total += int(np.sum(block))
            seen.extend(int(v) for v in block)
        with open(_os.path.join(config["out"], f"rank{rank}.json"),
                  "w") as f:
            json.dump({"total": total, "seen": seen}, f)
        session.report({"total": total, "blocks": len(seen)})

    trainer = JaxTrainer(
        data_loop,
        train_loop_config={"shards": shards, "out": str(tmp_path)},
        scaling_config=_scaling(),
        run_config=RunConfig(name="data_train", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    import json

    per_rank = [
        json.load(open(tmp_path / f"rank{r}.json"))
        for r in range(NUM_WORKERS)
    ]
    # disjoint shards covering the whole range exactly once
    all_seen = sorted(v for p in per_rank for v in p["seen"])
    assert all_seen == list(range(64))
    assert sum(p["total"] for p in per_rank) == sum(range(64))
