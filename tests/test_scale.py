"""Scale envelope — the in-suite miniature of the reference's release
benchmarks (reference release/benchmarks/README.md: 1M+ queued tasks on a
node, 10k+ concurrent tasks, 40k actors, 1k placement groups).

Sizes are CI-scaled: this box is often a single core, so the full
reference scale is expressed as rates and zero-failure invariants over
a 100k-task drain, a many-actor lifecycle at bounded startup
concurrency, and 200 placement groups. Set RAY_TPU_SCALE_ACTORS to
raise the actor count (e.g. 1000 on a many-core box).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 8 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.mark.slow  # ~60s drain; tier-1 has an 870s budget
def test_100k_queued_task_drain(cluster):
    """100k num_cpus=0 tasks queued and drained with no failures and no
    degradation: the second half must drain at a comparable rate to the
    first (a head/agent that degrades with queue depth — O(n^2) scans,
    unbounded buffers — fails this)."""
    @ray_tpu.remote(num_cpus=0)
    def noop():
        return 1

    ray_tpu.get([noop.remote() for _ in range(64)], timeout=120)  # warm

    n = 100_000
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    t_submit = time.perf_counter() - t0

    half = n // 2
    out1 = ray_tpu.get(refs[:half], timeout=600)
    t_half = time.perf_counter() - t0
    out2 = ray_tpu.get(refs[half:], timeout=600)
    t_all = time.perf_counter() - t0

    assert sum(out1) + sum(out2) == n
    rate1 = half / t_half
    rate2 = half / max(t_all - t_half, 1e-6)
    # NOTE: refs are drained in submission order, so by the time the
    # first half resolves much of the second half has already executed;
    # rate2 reflects residual drain and must not collapse
    assert rate2 > 0.25 * rate1, (
        f"drain degraded: first half {rate1:.0f}/s, "
        f"second half {rate2:.0f}/s")
    assert n / t_all > 1_000, f"overall drain {n / t_all:.0f}/s"
    # agent fully quiesced: nothing queued or tracked as running
    agent = cluster.head_agent
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and (
            agent.task_queue or agent.running):
        time.sleep(0.5)
    assert not agent.task_queue
    assert not agent.running
    print(f"submit {n / t_submit:.0f}/s drain {n / t_all:.0f}/s")


@pytest.mark.slow  # ~27s lifecycle soak; tier-1 has an 870s budget
def test_many_actor_lifecycle(cluster):
    """Concurrent actor creation at scale: every creation must succeed
    (startup-concurrency gating — unbounded concurrent interpreter
    starts once made ALL of 50 concurrent creations miss the register
    timeout on a 1-core box), every call answer, every kill reap."""
    n_total = int(os.environ.get("RAY_TPU_SCALE_ACTORS", "100"))
    wave = 50

    @ray_tpu.remote(num_cpus=0)
    class Member:
        def __init__(self):
            self._n = 0

        def bump(self):
            self._n += 1
            return self._n

    created = 0
    for start in range(0, n_total, wave):
        k = min(wave, n_total - start)
        actors = [Member.remote() for _ in range(k)]
        out = ray_tpu.get([a.bump.remote() for a in actors], timeout=600)
        assert out == [1] * k
        for a in actors:
            ray_tpu.kill(a)
        created += k
    assert created == n_total

    # all actor workers reaped — no process accumulation across waves
    agent = cluster.head_agent
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        live = [w for w in agent.workers.values()
                if w.actor_id is not None and w.proc.poll() is None]
        if not live:
            break
        time.sleep(0.5)
    assert not live, f"{len(live)} actor workers survived kill"


def test_200_placement_groups(cluster):
    """200 PGs created, readied, exercised and removed; resources return
    to the pool exactly (leaked bundle reservations fail the final
    capacity check)."""
    @ray_tpu.remote(num_cpus=0)
    def where():
        return 1

    agent = cluster.head_agent
    avail_before = dict(agent.resources_available)

    pgs = []
    for i in range(200):
        pg = ray_tpu.placement_group([{"CPU": 0.01}], strategy="PACK")
        pgs.append(pg)
    for pg in pgs:
        assert pg.ready(timeout=120)
    # run a task inside every 10th bundle to prove they're schedulable
    refs = [where.options(placement_group=pg).remote()
            for pg in pgs[::10]]
    assert sum(ray_tpu.get(refs, timeout=300)) == len(pgs[::10])
    for pg in pgs:
        ray_tpu.remove_placement_group(pg)

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if agent.resources_available.get("CPU") == avail_before.get("CPU"):
            break
        time.sleep(0.5)
    assert agent.resources_available.get("CPU") == \
        avail_before.get("CPU"), "PG removal leaked CPU reservations"
