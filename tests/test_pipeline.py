"""Pipeline parallelism (pp axis): parity vs the sequential layer scan.

The reference has no in-tree PP (SURVEY §2.7 — Alpa release tests only), so
these tests pin down the from-scratch GPipe design in parallel/pipeline.py:
same math as lax.scan over the layer stack, stages sharded over pp, grads
intact through the microbatch schedule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import llama
from ray_tpu.parallel import (
    MeshConfig,
    build_mesh,
    pipeline_apply,
    use_mesh,
)
from ray_tpu.train import batch_sharding, init_train_state, make_train_step

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _mlp_stack(n_layers, d, key):
    ks = jax.random.split(key, 2)
    return {
        "w": jax.random.normal(ks[0], (n_layers, d, d)) / np.sqrt(d),
        "b": jax.random.normal(ks[1], (n_layers, d)) * 0.01,
    }


def _mlp_layer(h, p):
    return jnp.tanh(h @ p["w"] + p["b"])


def test_pipeline_apply_matches_scan():
    mesh = build_mesh(MeshConfig(pp=4, tp=2), jax.devices()[:8])
    params = _mlp_stack(8, 16, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

    ref, _ = jax.lax.scan(lambda c, p: (_mlp_layer(c, p), None), x, params)

    with use_mesh(mesh):
        out = jax.jit(
            lambda p, h: pipeline_apply(_mlp_layer, p, h, num_microbatches=4)
        )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_scan():
    mesh = build_mesh(MeshConfig(pp=4), jax.devices()[:4])
    params = _mlp_stack(4, 8, jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 8))

    def loss_seq(p, h):
        out, _ = jax.lax.scan(lambda c, q: (_mlp_layer(c, q), None), h, p)
        return jnp.sum(out**2)

    def loss_pp(p, h):
        return jnp.sum(pipeline_apply(_mlp_layer, p, h, num_microbatches=2) ** 2)

    g_ref = jax.grad(loss_seq)(params, x)
    with use_mesh(mesh):
        g_pp = jax.jit(jax.grad(loss_pp))(params, x)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        ),
        g_ref,
        g_pp,
    )


def test_llama_forward_pipelined_matches_single():
    cfg = llama.LlamaConfig.tiny(n_layers=4, pipeline_microbatches=2)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(
        jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size, dtype=jnp.int32
    )
    ref = llama.forward(params, toks, cfg)  # no mesh -> sequential scan

    mesh = build_mesh(MeshConfig(pp=4, fsdp=2), jax.devices()[:8])
    with use_mesh(mesh):
        out = jax.jit(lambda p, t: llama.forward(p, t, cfg))(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_llama_train_step_on_pp_mesh():
    """Full sharded train step with dp+pp+fsdp+tp active: loss decreases."""
    cfg = llama.LlamaConfig.tiny(n_layers=2, pipeline_microbatches=2)
    mesh = build_mesh(MeshConfig(dp=1, pp=2, fsdp=2, tp=2), jax.devices()[:8])
    opt = optax.adamw(1e-2)
    state, state_sh = init_train_state(
        lambda k: llama.init_params(cfg, k),
        llama.param_logical_axes(cfg),
        opt,
        mesh,
        key=jax.random.PRNGKey(0),
    )
    step = make_train_step(
        lambda p, b: llama.loss_fn(p, b, cfg), opt, mesh, state_sh
    )
    toks = jax.random.randint(
        jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size, dtype=jnp.int32
    )
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
    with use_mesh(mesh):
        batch = jax.device_put(batch, batch_sharding(mesh))
        state, m0 = step(state, batch)
        for _ in range(5):
            state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"])
