"""TSan/ASan gate over the C++ components (SURVEY §4: the reference
exercises its raylet/plasma C++ under sanitizer configs). The stress
binaries live in ray_tpu/_native/sanitize/; run.sh builds each under
ThreadSanitizer and AddressSanitizer+UBSan and fails on any report."""

import json
import os
import shutil
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(ROOT, "ray_tpu", "_native", "sanitize", "run.sh")


@pytest.mark.slow  # ~90s of sanitizer builds; tier-1 has an 870s budget
@pytest.mark.skipif(shutil.which("g++") is None, reason="no toolchain")
def test_sanitizers_clean(tmp_path):
    out = str(tmp_path / "SANITIZE.json")
    r = subprocess.run([SCRIPT, out], capture_output=True, text=True,
                       timeout=1200)
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    summary = json.load(open(out))
    assert summary["clean"] is True
    assert {e["target"] for e in summary["results"]} == {
        "store_tsan", "store_asan", "sched_tsan", "sched_asan"}
    assert all(e["status"] == "clean" for e in summary["results"])
