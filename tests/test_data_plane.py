"""Zero-copy object data plane: single-copy put, zero-copy get,
pipelined multi-chunk pull, zero-copy chunk serving, and proactive
lineage reconstruction from node_dead events.

Covers the ISSUE-9 acceptance surface: deserialized arrays view the shm
segment (np.shares_memory), buffer pins outlive every view, cross-host
pulls overlap chunk requests (in-flight depth > 1, striped across
sources) and stay byte-identical under out-of-order arrival and
injected chunk drop/delay faults, concurrent pulls survive the
create/contains race, and a node death triggers reconstruction before
any consumer calls get.
"""

import gc
import os
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import config as cfg
from ray_tpu._private import fault_injection
from ray_tpu._private import rpc
from ray_tpu.cluster_utils import Cluster


@contextmanager
def _flag(**flags):
    old = {k: cfg.get(k) for k in flags}
    cfg.set_system_config(flags)
    try:
        yield
    finally:
        cfg.set_system_config(old)


def _seed(cluster, agent, data: bytes, meta: bytes = b""):
    """Plant a sealed object directly in `agent`'s store + directory."""
    oid = os.urandom(16)
    agent.store.put_bytes(oid, data, metadata=meta)
    cluster.io.run(agent.rpc_object_sealed(
        None, {"object_id": oid, "size": len(data)}))
    return oid


def _pull(cluster, agent, oid, timeout=60):
    return cluster.io.run(agent.rpc_fetch_object(
        None, {"object_id": oid, "timeout": timeout}))


def _stored_bytes(agent, oid):
    buf = agent.store.get(oid)
    assert buf is not None
    try:
        return bytes(buf.data)
    finally:
        buf.release()


# ---------------------------------------------------------------------------
# zero-copy semantics
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30},
                store_capacity=512 * 2**20)
    c.connect()
    yield c
    c.shutdown()


def test_get_views_store_segment_zero_copy(cluster):
    """A deserialized numpy array is a VIEW of the shm object, not a
    copy: it shares memory with the store segment."""
    w = cluster._driver
    arr = np.arange(1 << 20, dtype=np.uint8)
    ref = ray_tpu.put(arr)
    val = ray_tpu.get(ref)
    assert np.array_equal(val, arr)
    assert val.base is not None  # a view, not an owning array
    buf = w.store.get(ref.binary())
    try:
        seg = np.frombuffer(buf.data, dtype=np.uint8)
        assert np.shares_memory(val, seg)
    finally:
        buf.release()


def test_buffer_pin_outlives_all_views(cluster):
    """The ObjectBuffer pin is held while ANY zero-copy view is alive
    and released once the last one dies (store refcount drops)."""
    w = cluster._driver
    ref = ray_tpu.put(np.arange(1 << 20, dtype=np.uint8))
    val = ray_tpu.get(ref)
    val2 = ray_tpu.get(ref)
    gc.collect()
    exported = w.store._exported
    assert exported >= 2  # one live pin per deserialized view
    expected = int(val[100]) == 100 and int(val2[7]) == 7
    del val
    gc.collect()
    assert w.store._exported == exported - 1
    assert expected and int(val2[100]) == 100  # survivor still valid
    del val2
    gc.collect()
    assert w.store._exported == exported - 2


def test_inline_put_does_not_alias_caller_buffer(cluster):
    """Inline (small) values are materialized at put: mutating the
    source array afterwards must not change the stored value."""
    src = np.arange(100, dtype=np.int64)
    ref = ray_tpu.put(src)
    src[:] = -1
    assert ray_tpu.get(ref)[5] == 5


def test_oob_reply_rpc_roundtrip():
    """rpc-layer unit: an OobReply's buffers ride the out-of-band frame
    and land in result["oob"]; the release hook fires post-send."""
    from ray_tpu._private.rpc import EventLoopThread, RpcServer

    io = EventLoopThread("oob-test")
    server = RpcServer("127.0.0.1", 0)
    released = []
    payload = os.urandom(1 << 20)

    async def handler(conn, p):
        return rpc.OobReply({"n": 2}, [memoryview(payload), b"tail"],
                            release=lambda: released.append(1))

    server.handlers["oob"] = handler
    port = io.run(server.start())
    cli = rpc.SyncRpcClient("127.0.0.1", port, io)
    try:
        r = cli.call("oob", {})
        assert r["n"] == 2
        assert r["oob"] == [payload, b"tail"]
        assert released == [1]
    finally:
        cli.close()
        io.run(server.stop())
        io.stop()


def test_owned_get_parks_on_event_not_directory_polls(cluster):
    """Owned pending results are pushed to us: a no-deadline get parks
    on the entry event instead of polling the directory every 100ms."""
    w = cluster._driver

    @ray_tpu.remote(num_cpus=1)
    def slow():
        time.sleep(1.0)
        return 42

    calls = []
    orig = w._try_resolve_remote
    w._try_resolve_remote = lambda oid: (calls.append(oid), orig(oid))[1]
    try:
        assert ray_tpu.get(slow.remote()) == 42
    finally:
        w._try_resolve_remote = orig
    # old behavior: ~10 directory polls/second of waiting; now 0.5s
    # backstop slices -> a 1s task sees at most a few resolution
    # attempts instead of ~10
    assert len(calls) <= 4, f"{len(calls)} directory polls during get"


# ---------------------------------------------------------------------------
# pipelined cross-node pull
# ---------------------------------------------------------------------------


@pytest.fixture
def cluster3():
    # agents only, NO driver: these tests drive the agent-to-agent chunk
    # path directly, and a connect() here would clobber the module
    # cluster's global worker
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30},
                store_capacity=256 * 2**20)
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    yield c
    fault_injection.clear()
    c.shutdown()


def test_pipelined_pull_overlaps_chunk_requests(cluster3):
    """A cross-host pull keeps >1 chunk request in flight (the whole
    point of the pipeline) and the result is byte-identical."""
    c = cluster3
    src, dst = c.agents[0], c.agents[1]
    data = os.urandom(24 * 2**20)  # 6 chunks at the default 4MB
    oid = _seed(c, src, data, meta=b"meta!")
    assert _pull(c, dst, oid)
    st = dst.transfer_stats
    assert st["last_pull"]["max_inflight"] > 1
    assert st["last_pull"]["chunks"] == 6
    assert st["pull_max_inflight"] > 1
    assert _stored_bytes(dst, oid) == data
    buf = dst.store.get(oid)
    assert bytes(buf.metadata) == b"meta!"
    buf.release()


def test_two_source_pull_stripes_across_holders(cluster3):
    c = cluster3
    data = os.urandom(16 * 2**20)
    oid = _seed(c, c.agents[0], data)
    assert _pull(c, c.agents[1], oid)  # second holder
    assert _pull(c, c.agents[2], oid)  # pulls from BOTH
    last = c.agents[2].transfer_stats["last_pull"]
    assert last["sources"] == 2
    assert last["max_inflight"] > 1
    assert _stored_bytes(c.agents[2], oid) == data


def test_pull_byte_identical_under_out_of_order_arrival(cluster3):
    """Delaying one middle chunk makes later chunks arrive first; the
    offset-addressed writes still produce an identical object."""
    c = cluster3
    with _flag(object_transfer_chunk_bytes=256 * 1024):
        data = os.urandom(4 * 2**20)  # 16 chunks
        oid = _seed(c, c.agents[0], data)
        fault_injection.configure([
            {"site": "object.read_chunk", "action": "delay",
             "match": {"offset": 512 * 1024}, "delay_s": 0.3, "count": 1},
        ])
        try:
            assert _pull(c, c.agents[1], oid)
        finally:
            fault_injection.clear()
        assert _stored_bytes(c.agents[1], oid) == data
        assert c.agents[1].transfer_stats["last_pull"]["chunks"] == 16


def test_pull_retries_through_busy_refusal_faults(cluster3):
    """Injected chunk drops surface as the retryable {"busy": True}
    refusal; _read_chunk_backoff retries them and the pull completes
    byte-identical (the ROADMAP's read_object_chunk chaos coverage)."""
    c = cluster3
    with _flag(object_transfer_chunk_bytes=256 * 1024):
        data = os.urandom(2 * 2**20)  # 8 chunks
        oid = _seed(c, c.agents[0], data)
        fault_injection.configure([
            {"site": "object.read_chunk", "action": "drop",
             "after": 1, "count": 3},
        ])
        try:
            assert _pull(c, c.agents[1], oid)
            drops = [h for h in fault_injection.hits()
                     if h["action"] == "drop"]
            assert len(drops) == 3  # the refusal path actually ran
        finally:
            fault_injection.clear()
        assert _stored_bytes(c.agents[1], oid) == data


def test_concurrent_pulls_survive_create_race(cluster3):
    """Two pulls of the same object racing into create_object: one wins
    the create, the other waits for the seal — neither propagates
    ObjectExistsError, both report success."""
    c = cluster3
    import asyncio

    data = os.urandom(2 * 2**20)
    oid = _seed(c, c.agents[0], data)
    dst = c.agents[1]
    cli = c.io.run(dst._peer_agent(c.agents[0].node_id))

    async def race():
        return await asyncio.gather(dst._pull_from([cli], oid),
                                    dst._pull_from([cli], oid))

    r1, r2 = c.io.run(race())
    assert r1 and r2
    assert _stored_bytes(dst, oid) == data


def test_pull_racing_local_writer_waits_for_seal(cluster3):
    """A pull that loses the create race to a LOCAL writer (buffer
    exists but unsealed) waits for the seal instead of erroring."""
    c = cluster3
    import asyncio

    data = os.urandom(1 << 20)
    oid = _seed(c, c.agents[0], data)
    dst = c.agents[1]
    # local writer holds the unsealed buffer
    wbuf = dst.store.create_object(oid, len(data), 0)
    cli = c.io.run(dst._peer_agent(c.agents[0].node_id))

    async def pull_then_seal():
        task = asyncio.ensure_future(dst._pull_from([cli], oid))
        await asyncio.sleep(0.3)
        assert not task.done()  # parked waiting for the seal
        wbuf.data[:] = data
        wbuf.seal()
        return await asyncio.wait_for(task, timeout=10)

    assert c.io.run(pull_then_seal())
    assert _stored_bytes(dst, oid) == data


def test_serve_pin_cached_across_chunks_and_released(cluster3):
    """Chunk serving pins the object once per (conn, oid) transfer, not
    once per chunk, and drops the pin on the final chunk."""
    c = cluster3
    src = c.agents[0]
    with _flag(object_transfer_chunk_bytes=256 * 1024):
        data = os.urandom(2 * 2**20)  # 8 chunks
        oid = _seed(c, src, data)

        gets = []
        orig = src.store.get
        src.store.get = lambda o: (gets.append(o), orig(o))[1]

        class _Conn:
            state = {}

        try:
            out = b""
            off = 0
            while off < len(data):
                reply = src._read_object_chunk(
                    {"object_id": oid, "offset": off}, _Conn)
                assert isinstance(reply, rpc.OobReply)
                chunk = reply.bufs[0]
                out += bytes(chunk)
                off += chunk.nbytes
                reply.close()
            assert out == data
            assert gets.count(oid) == 1  # ONE store_get for all 8 chunks
            assert oid not in _Conn.state.get("serve_pins", {})
        finally:
            src.store.get = orig


# ---------------------------------------------------------------------------
# receive-side zero-copy (scatter-read)
# ---------------------------------------------------------------------------


def _oob_server(payload: bytes):
    """A one-method rpc server whose `chunk` handler replies with
    `payload` out-of-band; returns (io, server, client)."""
    from ray_tpu._private.rpc import EventLoopThread, RpcServer

    io = EventLoopThread("scatter-test")
    server = RpcServer("127.0.0.1", 0)

    async def handler(conn, p):
        return rpc.OobReply({"total": len(payload)}, [memoryview(payload)])

    server.handlers["chunk"] = handler
    port = io.run(server.start())
    cli = rpc.SyncRpcClient("127.0.0.1", port, io)
    return io, server, cli


def test_scatter_read_lands_in_registered_buffer_zero_copy():
    """rpc-layer proof of the receive fast path: a call with `oob_into`
    scatters the OOB payload directly into the registered buffer —
    result["oob"] views SHARE MEMORY with it (np.shares_memory), so no
    intermediate reader-side bytes object ever exists."""
    payload = os.urandom(2 << 20)
    io, server, cli = _oob_server(payload)
    dest = np.zeros(2 << 20, dtype=np.uint8)
    try:
        r = cli.call("chunk", {}, oob_into=memoryview(dest))
        assert r.get("oob_scattered") is True
        got = np.frombuffer(r["oob"][0], dtype=np.uint8)
        assert np.shares_memory(got, dest)  # aliases the registered buffer
        assert bytes(dest) == payload
    finally:
        cli.close()
        io.run(server.stop())
        io.stop()


def test_scatter_read_oversized_reply_falls_back_no_overflow():
    """A reply larger than the registered buffer must NOT scatter (no
    buffer overflow): the client falls back to the copying path and the
    destination stays untouched."""
    payload = os.urandom(1 << 20)
    io, server, cli = _oob_server(payload)
    dest = np.zeros(1 << 19, dtype=np.uint8)  # half the payload size
    try:
        r = cli.call("chunk", {}, oob_into=memoryview(dest))
        assert "oob_scattered" not in r
        assert bytes(r["oob"][0]) == payload  # copying fallback, intact
        assert not dest.any()  # registered buffer untouched
    finally:
        cli.close()
        io.run(server.stop())
        io.stop()


def test_oob_into_and_timeout_mutually_exclusive():
    """An abandoned-but-registered destination buffer would be written
    by a late reply — the API forbids the combination outright."""
    payload = b"x"
    io, server, cli = _oob_server(payload)
    try:
        with pytest.raises(ValueError, match="mutually exclusive"):
            cli.call("chunk", {}, timeout=5,
                     oob_into=memoryview(bytearray(8)))
    finally:
        cli.close()
        io.run(server.stop())
        io.stop()


def test_readinto_exactly_surfaces_error_set_while_not_waiting():
    """StreamReader.set_exception() only wakes an EXISTING waiter. An
    error recorded while the scatter read is NOT parked (partial chunk
    delivered, then the connection dies) must still abort the read —
    without the explicit exception() check, the next _wait_for_data()
    would create a waiter nothing ever wakes and the pull (scatter
    calls are forbidden from using rpc timeouts) would hang forever."""
    import asyncio

    from ray_tpu._private.rpc import _readinto_exactly

    async def run():
        reader = asyncio.StreamReader()
        reader.feed_data(b"ab")  # partial: 2 of 8 bytes arrived
        # connection_lost(exc) lands while no waiter is outstanding
        reader.set_exception(ConnectionResetError("peer reset"))
        dest = memoryview(bytearray(8))
        with pytest.raises(ConnectionResetError):
            await asyncio.wait_for(_readinto_exactly(reader, dest),
                                   timeout=5)

    asyncio.run(run())


def test_pull_scatter_writes_chunks_in_place(cluster3):
    """With transfer_scatter_read on (the default) every pipelined chunk
    after the lead lands directly in the shm write buffer — the agent's
    scattered counter equals chunks-1 and the object is byte-identical."""
    c = cluster3
    data = os.urandom(24 * 2**20)  # 6 chunks at the default 4MB
    oid = _seed(c, c.agents[0], data)
    assert _pull(c, c.agents[1], oid)
    last = c.agents[1].transfer_stats["last_pull"]
    assert last["chunks"] == 6
    assert last["scattered"] == last["chunks"] - 1  # all but the lead
    assert _stored_bytes(c.agents[1], oid) == data


def test_pull_scatter_off_knob_falls_back_byte_identical(cluster3):
    """Flipping the knob off live routes every chunk through the
    copying path (scattered == 0) with identical bytes."""
    c = cluster3
    with _flag(transfer_scatter_read=False):
        data = os.urandom(8 * 2**20)
        oid = _seed(c, c.agents[0], data)
        assert _pull(c, c.agents[1], oid)
        last = c.agents[1].transfer_stats["last_pull"]
        assert last["scattered"] == 0
        assert _stored_bytes(c.agents[1], oid) == data


def test_scatter_failed_pull_aborts_half_written_buffer(cluster3):
    """Chaos coverage for the scatter path: persistent object.read_chunk
    drops exhaust the busy budget mid-transfer — the half-scattered
    write buffer must be ABORTED (the store never exposes a sealed
    object with silent zero gaps), and a retry after the fault clears
    is byte-identical."""
    c = cluster3
    dst = c.agents[1]
    with _flag(object_transfer_chunk_bytes=256 * 1024,
               transfer_busy_budget_s=1.0,
               transfer_busy_backoff_initial_s=0.05):
        # every byte nonzero, so any leaked gap would be detectable
        data = bytes((i % 255) + 1 for i in range(2 * 2**20))  # 8 chunks
        oid = _seed(c, c.agents[0], data)
        cli = c.io.run(dst._peer_agent(c.agents[0].node_id))
        fault_injection.configure([
            {"site": "object.read_chunk", "action": "drop",
             "after": 3, "count": 10_000},
        ])
        try:
            assert c.io.run(dst._pull_from([cli], oid)) is False
            assert not dst.store.contains(oid)  # aborted, never sealed
        finally:
            fault_injection.clear()
        assert c.io.run(dst._pull_from([cli], oid)) is True
        assert dst.transfer_stats["last_pull"]["scattered"] >= 1
        assert _stored_bytes(dst, oid) == data


def test_scatter_retry_after_stall_byte_identical(cluster3):
    """A stalled chunk read under scatter (delay fault) completes late
    but lands at the right offset — byte-identity holds with the
    pipeline reordering around it."""
    c = cluster3
    with _flag(object_transfer_chunk_bytes=256 * 1024):
        data = bytes((i * 7 % 255) + 1 for i in range(4 * 2**20))
        oid = _seed(c, c.agents[0], data)
        fault_injection.configure([
            {"site": "object.read_chunk", "action": "delay",
             "match": {"offset": 768 * 1024}, "delay_s": 0.4, "count": 1},
        ])
        try:
            assert _pull(c, c.agents[1], oid)
        finally:
            fault_injection.clear()
        last = c.agents[1].transfer_stats["last_pull"]
        assert last["scattered"] == last["chunks"] - 1
        assert _stored_bytes(c.agents[1], oid) == data


def test_fetch_tags_attribute_pull_owner_and_qos(cluster3):
    """Consumer tags carried by fetch_object (the fetch_context /
    fetch_tags plumbing) flow through to the pull's pacer class and
    net_accounting owner attribution."""
    from ray_tpu._private import net_accounting as _net

    c = cluster3
    data = os.urandom(4 * 2**20)
    oid = _seed(c, c.agents[0], data)
    _net.reset_local()
    ok = c.io.run(c.agents[1].rpc_fetch_object(
        None, {"object_id": oid, "timeout": 60,
               "qos": "kv", "owner": "kv-handoff"}))
    assert ok
    last = c.agents[1].transfer_stats["last_pull"]
    assert last["owner"] == "kv-handoff"
    assert last["qos"] == "kv"
    assert _net.total("rx", qos_class="kv", owner="kv-handoff") >= len(data)


def test_task_fetch_tags_drive_dep_prefetch_attribution():
    """END-TO-END consumer path: `fn.options(fetch_tags=...)` rides the
    task spec to the executing node, whose dispatch-time dep prefetch
    pulls the arg cross-node with the declared owner/qos — scattered,
    paced in the declared class, and attributed in net_accounting."""
    from ray_tpu._private import api
    from ray_tpu._private import net_accounting as _net

    prev_worker = api._worker
    c = Cluster(head_resources={"CPU": 0, "memory": 2 * 2**30},
                store_capacity=256 * 2**20)
    n2 = c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    try:
        _net.reset_local()
        ref = ray_tpu.put(np.arange(1 << 19, dtype=np.float64))  # 4MB

        @ray_tpu.remote(num_cpus=1)
        def consume(x):
            return float(x[5])

        out = ray_tpu.get(consume.options(
            fetch_tags={"qos": "kv", "owner": "kv-handoff"}).remote(ref),
            timeout=90)
        assert out == 5.0
        last = n2.transfer_stats["last_pull"]
        assert last["owner"] == "kv-handoff"
        assert last["qos"] == "kv"
        assert last["scattered"] == last["chunks"] - 1
        assert _net.total("rx", qos_class="kv",
                          owner="kv-handoff") >= 4 * 2**20
    finally:
        c.shutdown()
        api._set_global_worker(prev_worker)


def test_prewarmed_segment_allocates_from_warm_prefix(cluster3):
    """object_store_prefault pre-touches the heap head at agent start;
    a pull-sized create_object then allocates from the warmed prefix
    (first-fit from the heap head) and round-trips correctly."""
    dst = cluster3.agents[1]
    n = dst.store.prewarm(8 * 2**20)  # idempotent re-touch
    assert n == 8 * 2**20
    oid = os.urandom(16)
    wbuf = dst.store.create_object(oid, 1 << 20, 0)
    wbuf.data[:] = b"\x5a" * (1 << 20)
    wbuf.seal()
    assert _stored_bytes(dst, oid) == b"\x5a" * (1 << 20)
    dst.store.delete(oid)


# ---------------------------------------------------------------------------
# checkpoint transport over the object store
# ---------------------------------------------------------------------------


def test_checkpoint_ships_and_fetches_through_object_store(cluster,
                                                           tmp_path):
    """ship_checkpoint / fetch_checkpoint round-trip a checkpoint
    directory through the object store with owner="checkpoint"
    attribution — the restore path of the receive-side data plane."""
    from ray_tpu._private import net_accounting as _net
    from ray_tpu.train.checkpoint import (
        Checkpoint, fetch_checkpoint, ship_checkpoint)

    src_dir = tmp_path / "src"
    ckpt = Checkpoint.from_dict(
        {"step": 7, "blob": os.urandom(2 * 2**20)}, str(src_dir))
    _net.reset_local()
    ref = ship_checkpoint(ckpt)
    out = fetch_checkpoint(ref, str(tmp_path / "dst"))
    assert out.to_dict()["step"] == 7
    assert out.to_dict()["blob"] == ckpt.to_dict()["blob"]
    # local fetch needs no pull, but the fetch_context tags must be in
    # effect during the get — verified cross-node by the tag test above


# ---------------------------------------------------------------------------
# proactive reconstruction on node_dead
# ---------------------------------------------------------------------------


def test_node_dead_triggers_reconstruction_before_any_get():
    """A node_dead event for the only holder of a primary-pinned object
    resubmits the producing task ON THE EVENT — before any consumer
    calls get — and a later get returns the recomputed value."""
    from ray_tpu._private import api

    prev_worker = api._worker
    # head has 0 CPUs: the producing task can only run on the worker
    # node, so the object's sole copy dies with it
    c = Cluster(head_resources={"CPU": 0, "memory": 2 * 2**30},
                store_capacity=256 * 2**20)
    n2 = c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    w = c.connect()
    try:
        @ray_tpu.remote(num_cpus=1)
        def produce():
            return np.arange(200_000, dtype=np.float64)

        ref = produce.remote()
        oid = ref.binary()
        # wait for the result to land on n2 (owner marked in_plasma)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            e = w.memory.get(oid)
            if e is not None and e.ready and e.in_plasma:
                break
            time.sleep(0.05)
        else:
            pytest.fail("producer never completed")
        assert n2.store.contains(oid)

        c.remove_node(n2)  # connection loss -> node_dead w/ lost_objects

        # the resubmit must happen from the EVENT: no get() has been
        # called — observe the reconstruction flag / requeued task
        tid = w.memory[oid].spec["task_id"]
        deadline = time.monotonic() + 15
        resubmitted = False
        while time.monotonic() < deadline:
            queued = any(s.get("task_id") == tid
                         for s in list(c.head_agent.task_queue))
            if w.memory[oid].reconstructing or queued:
                resubmitted = True
                break
            time.sleep(0.05)
        assert resubmitted, "no proactive resubmit on node_dead"

        # capacity returns -> the resubmitted task runs -> get succeeds
        c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
        val = ray_tpu.get(ref, timeout=90)
        assert val.shape == (200_000,) and val[123456] == 123456.0
    finally:
        c.shutdown()
        api._set_global_worker(prev_worker)  # restore the module cluster


# ---------------------------------------------------------------------------
# free + announce race (async seal announce)
# ---------------------------------------------------------------------------


def test_put_free_race_converges(cluster):
    """put() announces the seal asynchronously; an immediate free must
    not leak the object (tombstone heals the late announce)."""
    w = cluster._driver
    gc.collect()
    time.sleep(0.5)  # let earlier tests' async frees settle
    baseline = w.store.used_bytes()
    mb = np.zeros(1 << 20, dtype=np.uint8)
    for _ in range(20):
        r = ray_tpu.put(mb)
        ray_tpu.free([r])
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if w.store.used_bytes() <= baseline:
            break
        time.sleep(0.1)
    assert w.store.used_bytes() <= baseline


# ---------------------------------------------------------------------------
# spilled-object serving (chunks straight off the spill file, no restore)
# ---------------------------------------------------------------------------


def test_pull_served_straight_from_spill_file(cluster3):
    """A remote pull of a spilled object streams chunks directly from
    the holder's spill file through the same OOB framing as store-backed
    serves — the holder never reloads the object into its store."""
    c = cluster3
    src, dst = c.agents[0], c.agents[1]
    data = os.urandom(10 * 2**20)  # 3 chunks at the default 4MB
    oid = _seed(c, src, data, meta=b"spill-meta")
    assert c.io.run(src._spill_one(oid))
    assert src.store.get(oid) is None  # evicted: only the file remains
    assert oid in src.spilled_files
    assert _pull(c, dst, oid)
    assert _stored_bytes(dst, oid) == data
    buf = dst.store.get(oid)
    assert bytes(buf.metadata) == b"spill-meta"
    buf.release()
    # served straight from disk: the holder still has no store copy and
    # the spill file survives for the next puller
    assert src.store.get(oid) is None
    assert oid in src.spilled_files
    from ray_tpu._private import flight_recorder as _fr

    spans = [s for s in _fr._get().ring
             if s["name"] == "transfer.serve_chunk"
             and s["attrs"].get("spill")
             and s["attrs"].get("oid") == oid.hex()[:16]]
    assert len(spans) == 3
    assert sum(s["attrs"]["bytes"] for s in spans) == len(data)


def test_spill_serve_small_chunks_meta_only_at_offset_zero(cluster3):
    """Many-chunk spill serve: metadata rides only the offset-0 chunk
    (the framing contract), later offsets seek past `8B len | meta` into
    the data region, and the reassembled bytes are identical."""
    c = cluster3
    src, dst = c.agents[0], c.agents[2]
    data = os.urandom(3 * 256 * 1024 + 17)
    oid = _seed(c, src, data, meta=b"m" * 100)
    assert c.io.run(src._spill_one(oid))
    with _flag(object_transfer_chunk_bytes=256 * 1024):
        assert _pull(c, dst, oid)
    assert _stored_bytes(dst, oid) == data
    buf = dst.store.get(oid)
    assert bytes(buf.metadata) == b"m" * 100
    buf.release()
    assert src.store.get(oid) is None
