"""Ring attention parity vs attention_reference on the 8-device CPU mesh
(VERDICT round-1 item 8 'done' bar: match at seq 8k)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.ops.attention import attention_reference
from ray_tpu.ops.ring_attention import ring_attention


@pytest.fixture(scope="module")
def sp_mesh():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("sp",))


def _mk(b, t, h, d, hkv=None, seed=0):
    hkv = hkv or h
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, hkv, d), jnp.float32)
    return q, k, v


def _shard(x, mesh):
    return jax.device_put(x, NamedSharding(mesh, P(None, "sp", None, None)))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(sp_mesh, causal):
    q, k, v = _mk(2, 64, 2, 16)
    want = attention_reference(q, k, v, causal=causal)
    got = ring_attention(
        _shard(q, sp_mesh), _shard(k, sp_mesh), _shard(v, sp_mesh),
        sp_mesh, causal=causal,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_ring_gqa(sp_mesh):
    q, k, v = _mk(1, 64, 4, 16, hkv=2)
    want = attention_reference(q, k, v, causal=True)
    got = ring_attention(
        _shard(q, sp_mesh), _shard(k, sp_mesh), _shard(v, sp_mesh),
        sp_mesh, causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4
    )


def test_ring_seq8k(sp_mesh):
    """The headline case: 8k sequence over 8 sp shards."""
    q, k, v = _mk(1, 8192, 1, 8)
    want = attention_reference(q, k, v, causal=True)
    got = ring_attention(
        _shard(q, sp_mesh), _shard(k, sp_mesh), _shard(v, sp_mesh),
        sp_mesh, causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=5e-5, rtol=1e-3
    )


def test_ring_gradients(sp_mesh):
    q, k, v = _mk(1, 64, 2, 16)

    def f_ring(q, k, v):
        return ring_attention(q, k, v, sp_mesh, causal=True).sum()

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(
        _shard(q, sp_mesh), _shard(k, sp_mesh), _shard(v, sp_mesh)
    )
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), atol=1e-4, rtol=1e-3
        )
