"""ray:// remote drivers (VERDICT Missing #8): a driver with NO
co-located node agent / shm store drives the full API over TCP."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import api as _api
from ray_tpu._private.client import RemoteDriverWorker, connect
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.fixture
def client(cluster):
    """A second, agent-less driver session against the same cluster."""
    w = connect(f"ray://127.0.0.1:{cluster.head_port}")
    assert isinstance(w, RemoteDriverWorker)
    assert w.store is None  # the whole point: no shm attachment
    prev = _api._worker
    _api._set_global_worker(w)
    yield w
    _api._set_global_worker(prev)
    w.shutdown()


def test_client_put_get_roundtrip(client):
    big = np.arange(500_000)  # plasma-sized: rides the RPC data plane
    ref = ray_tpu.put(big)
    out = ray_tpu.get(ref, timeout=60)
    np.testing.assert_array_equal(out, big)
    small = ray_tpu.put({"k": 1})
    assert ray_tpu.get(small, timeout=30) == {"k": 1}


def test_client_tasks_and_actors(client):
    @ray_tpu.remote
    def double(x):
        return x * 2

    assert ray_tpu.get([double.remote(i) for i in range(8)],
                       timeout=60) == [0, 2, 4, 6, 8, 10, 12, 14]

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.add.remote(5), timeout=60) == 5
    assert ray_tpu.get(c.add.remote(2), timeout=60) == 7
    ray_tpu.kill(c)


def test_client_plasma_task_results(client):
    """Plasma-sized TASK RESULTS flow back to the agent's store and the
    client reads them over the wire."""
    @ray_tpu.remote
    def make(n):
        return np.ones(n, dtype=np.float64)

    out = ray_tpu.get(make.remote(300_000), timeout=60)
    assert out.shape == (300_000,)
    assert float(out.sum()) == 300_000.0


def test_client_wait_and_state(client):
    @ray_tpu.remote
    def one():
        return 1

    refs = [one.remote() for _ in range(6)]
    ready, pending = ray_tpu.wait(refs, num_returns=6, timeout=60)
    assert len(ready) == 6 and not pending
    # control-plane state API works through the same TCP head client
    assert any(n["alive"] for n in ray_tpu.nodes())
