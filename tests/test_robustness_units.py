"""Unit regressions for the robustness satellites:

- AsyncRpcClient.fire's 32MB transport-buffer backstop (awaited drain);
- node agent read_object_chunk retryable {"busy"} refusal + the pull
  side's bounded backoff on it;
- autoscaler monitor exit-code contract (head-unreachable restartable);
- decode_chunk per-slot position clamp at the cache edge.
"""

import asyncio
import socket
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from ray_tpu._private import rpc


# ---------------------------------------------------------------------------
# rpc fire backstop
# ---------------------------------------------------------------------------


class _FakeTransport:
    def __init__(self):
        self.buffered = 0

    def get_write_buffer_size(self):
        return self.buffered


class _FakeWriter:
    def __init__(self, drain_gate: asyncio.Event):
        self.transport = _FakeTransport()
        self.writes = []
        self._gate = drain_gate

    def write(self, b):
        self.writes.append(b)

    async def drain(self):
        await self._gate.wait()

    def is_closing(self):
        return False


def test_async_fire_backstop_pauses_past_threshold():
    """Past FIRE_BUFFER_BACKSTOP buffered bytes the async fire path must
    stop writing to the transport and await a drain; queued fires flush
    once the buffer recedes — the wedged-peer buffer stays bounded."""

    async def run():
        cli = rpc.AsyncRpcClient("127.0.0.1", 1)
        gate = asyncio.Event()
        w = _FakeWriter(gate)
        cli._writer = w

        cli.fire("m", b"a")
        await asyncio.sleep(0)  # let the call_soon flush run
        assert len(w.writes) == 1

        # buffer jumps past the backstop: the next flush notices and
        # parks an awaited drain
        w.transport.buffered = rpc.FIRE_BUFFER_BACKSTOP + 1
        cli.fire("m", b"b")
        await asyncio.sleep(0)
        assert len(w.writes) == 2
        assert cli._fire_drain_task is not None

        # while draining, fires queue instead of hitting the transport
        cli.fire("m", b"c")
        cli.fire("m", b"d")
        await asyncio.sleep(0.05)
        assert len(w.writes) == 2
        assert len(cli._fire_out) == 2

        # buffer recedes -> drain completes -> backlog flushes (one
        # coalesced write)
        w.transport.buffered = 0
        gate.set()
        await asyncio.sleep(0.05)
        assert len(w.writes) == 3
        assert cli._fire_out == []

    asyncio.run(run())


def test_async_fire_backstop_writes_through_when_wedged(monkeypatch):
    """A peer wedged past the drain deadline still gets the queued
    frames (mirroring SyncRpcClient.fire's bounded WAIT): collective
    chunks must never be silently dropped to a slow-but-alive peer —
    and the next flush re-arms pacing while the buffer stays high."""

    async def run():
        cli = rpc.AsyncRpcClient("127.0.0.1", 1)
        gate = asyncio.Event()  # never set: wedged peer
        w = _FakeWriter(gate)
        cli._writer = w
        w.transport.buffered = rpc.FIRE_BUFFER_BACKSTOP + 1

        monkeypatch.setattr(rpc, "FIRE_DRAIN_TIMEOUT_S", 0.1)
        cli.fire("m", b"a")
        await asyncio.sleep(0)
        assert cli._fire_drain_task is not None
        cli.fire("m", b"backlogged")
        await asyncio.sleep(0.3)
        # backlog written through after the bounded wait, not dropped
        assert cli._fire_out == []
        assert len(w.writes) == 2
        # later fires keep making paced progress while the buffer stays
        # high: one write-through per drain window, never a drop
        cli.fire("m", b"c")
        await asyncio.sleep(0.3)
        assert cli._fire_out == []
        assert len(w.writes) == 3

    asyncio.run(run())


def test_peer_lost_evicts_cached_client():
    """A dead peer connection must be EVICTED from the worker's client
    cache when on_close fires: a reformed collective incarnation reusing
    the same (addr, port) must redial, not receive the closed client —
    keeping it would re-abort every fresh incarnation (livelock)."""
    from ray_tpu._private.worker import CoreWorker

    w = CoreWorker.__new__(CoreWorker)
    closed = []
    stale = SimpleNamespace(close=lambda: closed.append(True))
    key = ("10.0.0.7", 4321)
    w._peer_clients = {key: stale}
    seen = []
    w._peer_lost_listeners = [seen.append]
    w._notify_peer_lost(key)
    assert key not in w._peer_clients  # evicted before listeners ran
    assert closed == [True]
    assert seen == [key]


# ---------------------------------------------------------------------------
# read_object_chunk busy refusal + pull backoff
# ---------------------------------------------------------------------------


def _agent_shell():
    """A NodeAgent shell with only what the tested methods touch."""
    from ray_tpu.core.node_agent import NodeAgent

    return NodeAgent.__new__(NodeAgent)


def test_read_object_chunk_refuses_retryably_on_pacing_deadline():
    from ray_tpu.core import node_agent as na

    agent = _agent_shell()
    window = int(na.cfg.get("transfer_outbound_window_bytes"))

    class _Conn:
        state = {}

        class writer:
            class transport:
                @staticmethod
                def get_write_buffer_size():
                    return window + 1

                @staticmethod
                def set_write_buffer_limits(high=None, low=None):
                    _Conn.state["limits"] = (high, low)

            @staticmethod
            def is_closing():
                return False

        @staticmethod
        async def drain():
            raise asyncio.TimeoutError  # pacing deadline expired

    out = asyncio.run(na.NodeAgent.rpc_read_object_chunk(
        agent, _Conn, {"object_id": b"x", "offset": 0}))
    assert out == {"busy": True, "retry_after_s": 0.5}
    # the per-peer wakeup is transport-level: water marks set once per
    # connection (no 5ms poll loops) to the serve gate — ~2 chunks, so
    # responses stream from a small buffer instead of memmoving a
    # window-sized bytearray on every partial send
    gate = min(window, 2 * na._chunk_size())
    assert _Conn.state["limits"] == (gate, gate // 2)
    assert _Conn.state["paced"] is True


def test_read_object_chunk_serves_when_under_window():
    from ray_tpu.core import node_agent as na

    agent = _agent_shell()
    sentinel = {"total": 3, "meta": b"", "chunk": b"abc"}
    agent._read_object_chunk = lambda p, conn=None: sentinel

    class _Conn:
        state = {}

        class writer:
            class transport:
                @staticmethod
                def get_write_buffer_size():
                    return 0

    out = asyncio.run(na.NodeAgent.rpc_read_object_chunk(
        agent, _Conn, {"object_id": b"x", "offset": 0}))
    assert out is sentinel


def test_pull_backs_off_on_busy_then_succeeds():
    from ray_tpu.core import node_agent as na

    agent = _agent_shell()
    calls = []

    class _Cli:
        async def call(self, method, p):
            calls.append(p["offset"])
            if len(calls) < 3:
                return {"busy": True, "retry_after_s": 0.01}
            return {"total": 3, "meta": b"", "chunk": b"abc"}

    out = asyncio.run(na.NodeAgent._read_chunk_backoff(
        agent, _Cli(), b"oid", 0))
    assert out["chunk"] == b"abc"
    assert len(calls) == 3


def test_pull_gives_up_after_wall_clock_budget():
    from ray_tpu.core import node_agent as na

    agent = _agent_shell()
    n = [0]

    class _Cli:
        async def call(self, method, p):
            n[0] += 1
            return {"busy": True}

    t0 = time.monotonic()
    out = asyncio.run(na.NodeAgent._read_chunk_backoff(
        agent, _Cli(), b"oid", 0, budget_s=1.0))
    elapsed = time.monotonic() - t0
    assert out is None
    assert n[0] > 1           # it retried...
    assert elapsed < 10       # ...but gave up once the budget elapsed


# ---------------------------------------------------------------------------
# monitor exit-code contract
# ---------------------------------------------------------------------------


def test_run_monitor_head_unreachable_is_distinct_restartable_rc():
    from ray_tpu.autoscaler import monitor as mon

    # nothing listens on a fresh ephemeral port → connect fails fast
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rc = mon.run_monitor(f"127.0.0.1:{port}", "no.such.module:Provider")
    assert rc == mon.RC_HEAD_UNREACHABLE
    assert rc not in (0, mon.RC_WIRING)


def test_run_monitor_broken_wiring_is_terminal_rc():
    from ray_tpu.autoscaler import monitor as mon

    # a bare listener accepts the head connection; the bogus provider
    # spec then fails construction → terminal wiring code
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(5)
    try:
        rc = mon.run_monitor(
            f"127.0.0.1:{srv.getsockname()[1]}",
            "no.such.module:Provider")
        assert rc == mon.RC_WIRING
    finally:
        srv.close()


def test_monitor_supervisor_restarts_head_unreachable(monkeypatch):
    """rc=RC_HEAD_UNREACHABLE must be restarted (with backoff) — a
    transient head outage can't permanently disable autoscaling."""
    from ray_tpu.autoscaler.monitor import (
        MonitorProcess,
        RC_HEAD_UNREACHABLE,
    )

    spawned = []

    class _Proc:
        def __init__(self):
            self.returncode = RC_HEAD_UNREACHABLE

        def poll(self):
            return self.returncode

    mon = MonitorProcess("127.0.0.1:1", "x:y")
    mon.RESTART_BACKOFF_S = 0.05
    monkeypatch.setattr(
        mon, "_spawn", lambda: spawned.append(1) or _Proc())
    mon.start()
    try:
        deadline = time.monotonic() + 10
        while mon.restarts < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert mon.restarts >= 2, "head-unreachable exits were not restarted"
    finally:
        mon.stop()


def test_monitor_supervisor_leaves_wiring_failures_dead(monkeypatch):
    from ray_tpu.autoscaler.monitor import MonitorProcess, RC_WIRING

    class _Proc:
        returncode = RC_WIRING

        def poll(self):
            return self.returncode

    mon = MonitorProcess("127.0.0.1:1", "x:y")
    monkeypatch.setattr(mon, "_spawn", lambda: _Proc())
    mon.start()
    try:
        mon._sup.join(timeout=10)
        assert not mon._sup.is_alive()  # supervisor gave up by design
        assert mon.restarts == 0
    finally:
        mon._stop.set()


# ---------------------------------------------------------------------------
# decode_chunk position clamp
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("chunk", [4])
def test_decode_chunk_clamps_pos_at_cache_edge(chunk):
    """Slots that hit the cache edge mid-chunk keep pos pinned at
    max_len-1 (in-range scatters, exact finish check) instead of
    running past the cache."""
    jax = pytest.importorskip("jax")
    from ray_tpu.models import llama
    from ray_tpu.models.decode_engine import decode_chunk, init_ragged_cache

    cfg = llama.LlamaConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=32, max_seq_len=8, dtype="float32", use_flash=False,
        remat=False)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    max_len = 8
    cache = init_ragged_cache(cfg, slots=2, max_len=max_len)
    # slot 0 is 2 rows from the edge; slot 1 frozen mid-cache
    cache["pos"] = jax.numpy.asarray(np.array([max_len - 2, 3], np.int32))
    tok = jax.numpy.zeros((2,), jax.numpy.int32)
    active = np.array([True, False])
    toks, cache, last = decode_chunk(params, cache, tok, active, cfg,
                                     chunk)
    pos = np.asarray(cache["pos"])
    assert pos[0] == max_len - 1, f"pos ran past the cache edge: {pos}"
    assert pos[1] == 3  # frozen slot untouched
    assert np.asarray(toks).shape == (2, chunk)
