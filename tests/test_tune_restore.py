"""Tune experiment restore + TPE searcher (VERDICT r2 item 9).

- Kill the driver mid-study (real SIGKILL on a subprocess), restore, and
  the final ResultGrid has the full trial count with resumed trials
  continuing from their checkpoints.
- TPE beats random search on a seeded quadratic within half the budget.
"""

import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu import tune


def test_restore_after_driver_kill(tmp_path):
    exp_parent = str(tmp_path / "store")
    script = tmp_path / "study.py"
    script.write_text(textwrap.dedent(f"""
        import jax; jax.config.update("jax_platforms", "cpu")
        import ray_tpu
        from ray_tpu import tune
        from ray_tpu.train.checkpoint import Checkpoint

        def trainable(config):
            import os, tempfile, time
            ck = tune.get_checkpoint()
            start = 0
            if ck is not None:
                start = ck.to_dict()["iter"]
            for i in range(start, 6):
                d = tempfile.mkdtemp(prefix="trial_ck_")
                tune.report(
                    {{"loss": config["x"] + 6 - i, "iter": i}},
                    checkpoint=Checkpoint.from_dict(
                        {{"iter": i + 1}}, path=d),
                )
                time.sleep(0.4)

        ray_tpu.init(num_cpus=4)
        tuner = tune.Tuner(
            trainable,
            param_space={{"x": tune.grid_search([1.0, 2.0, 3.0, 4.0])}},
            tune_config=tune.TuneConfig(
                metric="loss", mode="min", max_concurrent_trials=2),
            run_config=tune.RunConfig(name="study",
                                      storage_path={exp_parent!r}),
        )
        tuner.fit()
        print("FIT_DONE", flush=True)
    """))
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root}
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, cwd=repo_root,
                            env=env)
    # wait until the study is underway (state file exists + some progress)
    state_file = os.path.join(exp_parent, "study", "experiment_state.pkl")
    deadline = time.time() + 120
    while time.time() < deadline and not os.path.exists(state_file):
        time.sleep(0.2)
    assert os.path.exists(state_file), "study never started"
    time.sleep(2.5)  # let a couple of reports/checkpoints land
    proc.send_signal(signal.SIGKILL)  # driver dies mid-study
    proc.wait()

    # restore in-process and finish the study
    import jax

    jax.config.update("jax_platforms", "cpu")
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    try:
        def trainable(config):
            ck = tune.get_checkpoint()
            start = 0
            if ck is not None:
                start = ck.to_dict()["iter"]
            assert start > 0 or True
            for i in range(start, 6):
                import tempfile

                from ray_tpu.train.checkpoint import Checkpoint

                d = tempfile.mkdtemp(prefix="trial_ck_")
                tune.report({"loss": config["x"] + 6 - i, "iter": i},
                            checkpoint=Checkpoint.from_dict(
                                {"iter": i + 1}, path=d))

        tuner = tune.Tuner.restore(os.path.join(exp_parent, "study"),
                                   trainable)
        grid = tuner.fit()
    finally:
        ray_tpu.shutdown()

    # full study: all 4 grid trials present with final metrics
    assert len(grid) == 4
    xs = sorted(r.config["x"] for r in grid)
    assert xs == [1.0, 2.0, 3.0, 4.0]
    for r in grid:
        assert r.error is None
        assert r.metrics["iter"] == 5  # every trial reached the end
    best = grid.get_best_result()
    assert best.config["x"] == 1.0


def test_tpe_beats_random_on_quadratic():
    """Seeded quadratic: median best-of-10 TPE beats median best-of-20
    random over several seeds (the 'model-based search finds the optimum
    in half the trials' bar, stated statistically so no single lucky
    random draw decides it). Pure searcher test — no cluster needed."""
    import random
    import statistics

    def f(x):
        return (x - 0.3) ** 2

    space = {"x": tune.uniform(-2.0, 2.0)}
    seeds = range(10)

    random_bests = []
    for s in seeds:
        rng = random.Random(s)
        random_bests.append(
            min(f(space["x"].sample(rng)) for _ in range(30)))

    tpe_bests = []
    for s in seeds:
        tpe = tune.TPESearcher(metric="loss", mode="min",
                               n_startup_trials=4, seed=s)
        tpe.set_space(space)
        best = float("inf")
        for i in range(15):
            cfg = tpe.suggest(f"t{i}")
            loss = f(cfg["x"])
            best = min(best, loss)
            tpe.on_trial_complete(f"t{i}", {"loss": loss, "config": cfg})
        tpe_bests.append(best)

    assert statistics.median(tpe_bests) < statistics.median(random_bests), (
        sorted(tpe_bests), sorted(random_bests))


def test_tpe_categorical_and_loguniform():
    tpe = tune.TPESearcher(metric="loss", mode="min", n_startup_trials=3,
                           seed=3)
    space = {"lr": tune.loguniform(1e-5, 1e-1),
             "act": tune.choice(["relu", "gelu", "tanh"])}
    tpe.set_space(space)

    def f(cfg):
        import math

        return (math.log10(cfg["lr"]) + 3) ** 2 + \
            (0.0 if cfg["act"] == "gelu" else 1.0)

    best = float("inf")
    best_cfg = None
    for i in range(25):
        cfg = tpe.suggest(f"t{i}")
        assert 1e-5 <= cfg["lr"] <= 1e-1
        assert cfg["act"] in ("relu", "gelu", "tanh")
        loss = f(cfg)
        if loss < best:
            best, best_cfg = loss, cfg
        tpe.on_trial_complete(f"t{i}", {"loss": loss, "config": cfg})
    # converges toward lr ~ 1e-3, act = gelu
    assert best < 0.5
    assert best_cfg["act"] == "gelu"


def test_searcher_state_roundtrip():
    tpe = tune.TPESearcher(metric="loss", mode="min", seed=1)
    tpe.set_space({"x": tune.uniform(0, 1)})
    for i in range(6):
        cfg = tpe.suggest(f"t{i}")
        tpe.on_trial_complete(f"t{i}", {"loss": cfg["x"], "config": cfg})
    blob = tpe.save()
    tpe2 = tune.TPESearcher()
    tpe2.restore(blob)
    assert len(tpe2._obs) == 6
    assert tpe2.suggest("t9") is not None
