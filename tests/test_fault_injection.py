"""Deterministic fault-injection harness.

The chaos layer's core promise: the same injection config yields the
same action at the same site occurrence, every run — so a failure a
chaos test provokes is exactly reproducible.
"""

import json
import threading
import time

import numpy as np
import pytest

from ray_tpu._private import fault_injection as fi


@pytest.fixture(autouse=True)
def _clean():
    fi.clear()
    yield
    fi.clear()


# ---------------------------------------------------------------------------
# spec semantics
# ---------------------------------------------------------------------------


def test_spec_validation():
    with pytest.raises(ValueError):
        fi.configure([{"action": "die"}])  # site missing
    with pytest.raises(ValueError):
        fi.configure([{"site": "x", "action": "meltdown"}])
    fi.configure(None)
    assert not fi.enabled()


def test_disabled_is_noop():
    assert fi.fire("anything", rank=3) is None
    assert fi.hits() == []


def test_count_and_after_semantics():
    fi.configure([{"site": "s", "action": "drop", "after": 2, "count": 2}])
    acts = [fi.fire("s") for _ in range(6)]
    assert acts == [None, None, "drop", "drop", None, None]
    # the hit log records exactly the tripped occurrences, in order
    assert [h["occurrence"] for h in fi.hits()] == [2, 3]


def test_match_is_subset_equality():
    fi.configure([{"site": "s", "match": {"rank": 1, "chunk": 0},
                   "action": "drop", "count": 0}])
    assert fi.fire("s", rank=0, chunk=0) is None
    assert fi.fire("s", rank=1, chunk=1) is None
    assert fi.fire("s", rank=1, chunk=0, extra="ignored") == "drop"
    assert fi.fire("other", rank=1, chunk=0) is None


def test_die_raises_injected_fault():
    fi.configure([{"site": "s", "match": {"rank": 2}, "action": "die"}])
    fi.fire("s", rank=0)
    with pytest.raises(fi.InjectedFault, match="injected fault at s"):
        fi.fire("s", rank=2)
    # count=1: the next matching occurrence passes
    assert fi.fire("s", rank=2) is None


def test_delay_sleeps_then_proceeds():
    fi.configure([{"site": "s", "action": "delay", "delay_s": 0.2}])
    t0 = time.monotonic()
    assert fi.fire("s") is None
    assert time.monotonic() - t0 >= 0.2


def test_composable_specs_record_in_trip_order():
    fi.configure([
        {"site": "a", "action": "drop"},
        {"site": "b", "action": "dup", "count": 2},
    ])
    assert fi.fire("b") == "dup"
    assert fi.fire("a") == "drop"
    assert fi.fire("b") == "dup"
    log = fi.hits()
    assert [(h["site"], h["action"]) for h in log] == [
        ("b", "dup"), ("a", "drop"), ("b", "dup")]
    assert [h["seq"] for h in log] == [1, 2, 3]


def test_env_spec_adopted_once(monkeypatch):
    monkeypatch.setenv("RAY_TPU_FAULT_SPEC", json.dumps(
        [{"site": "env-site", "action": "drop"}]))
    fi._env_loaded = False
    try:
        assert fi.enabled()
        assert fi.fire("env-site") == "drop"
    finally:
        fi.clear()
        fi._env_loaded = True  # don't re-adopt in later tests


# ---------------------------------------------------------------------------
# determinism through the real ring engine (threaded fake ranks)
# ---------------------------------------------------------------------------


class _Net:
    def __init__(self):
        self.cond = threading.Condition()
        self.msgs = {}

    def put(self, key, val):
        with self.cond:
            self.msgs[key] = val
            self.cond.notify_all()

    def take(self, key, timeout):
        deadline = time.monotonic() + timeout
        with self.cond:
            while key not in self.msgs:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise TimeoutError(key)
                self.cond.wait(min(rem, 0.1))
            return self.msgs.pop(key)


class _FakeGroup:
    def __init__(self, net, name, world, rank):
        self.net = net
        self.name = name
        self.world_size = world
        self.rank = rank
        self.seq = 0

    def _next_seq(self):
        self.seq += 1
        return self.seq

    def _send_obj(self, dst, seq, tag, obj, fire=False):
        from ray_tpu._private import serialization

        self.net.put((dst, self.name, seq, self.rank, tag),
                     serialization.pack_payload(obj))

    def _recv_obj(self, src, seq, tag, timeout=None, op=None):
        from ray_tpu._private import serialization

        msg = self.net.take((self.rank, self.name, seq, src, tag),
                            timeout or 30)
        return serialization.unpack_payload(msg)


def _chaos_allreduce_run(spec):
    """One threaded world-2 allreduce under `spec`; returns
    (per-rank outcome strings, injection hit log)."""
    from ray_tpu.collective import ring

    fi.clear()
    fi.configure(spec)
    net = _Net()
    outcome = [None, None]

    def go(r):
        data = np.arange(64, dtype=np.float32) * (r + 1)
        try:
            ring.ring_allreduce(_FakeGroup(net, "chaos", 2, r), data,
                                timeout=2.0)
            outcome[r] = "ok"
        except fi.InjectedFault:
            outcome[r] = "died"
        except TimeoutError:
            outcome[r] = "timeout"

    threads = [threading.Thread(target=go, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    log = fi.hits()
    fi.clear()
    return outcome, log


def test_chaos_run_is_deterministic_across_repeats():
    """Acceptance: the same injection config yields the same abort site
    (site + occurrence + full ctx) across repeated runs."""
    spec = [{"site": "ring.send",
             "match": {"rank": 1, "op": "ar:rs0", "chunk": 0},
             "action": "die"}]
    runs = [_chaos_allreduce_run(spec) for _ in range(3)]
    for outcome, log in runs:
        assert outcome[1] == "died"      # the victim dies at its site
        assert outcome[0] == "timeout"   # fake ranks have no abort path
        assert len(log) == 1
    sites = [(h["site"], h["occurrence"], tuple(sorted(h["ctx"].items())))
             for _, (h,) in runs]
    assert sites[0] == sites[1] == sites[2]
    assert sites[0][0] == "ring.send"
    assert dict(sites[0][2])["rank"] == 1
    assert dict(sites[0][2])["op"] == "ar:rs0"


def test_chaos_drop_then_dup_compose():
    """drop + dup on distinct chunks of the same op: the dup'd frame
    overwrites idempotently, the dropped one times the receiver out —
    and both injections are recorded deterministically."""
    spec = [
        {"site": "ring.send", "match": {"rank": 0, "chunk": 0},
         "action": "drop"},
        {"site": "ring.send", "match": {"rank": 1, "chunk": 0},
         "action": "dup"},
    ]
    outcome, log = _chaos_allreduce_run(spec)
    # rank 0's dropped reduce-scatter frame strands rank 1; rank 0
    # still receives rank 1's (duplicated, idempotent) frame for the
    # reduce-scatter but starves in the all-gather
    assert outcome == ["timeout", "timeout"]
    assert {(h["site"], h["action"], h["ctx"]["rank"]) for h in log} == {
        ("ring.send", "drop", 0), ("ring.send", "dup", 1)}
