"""Flight-recorder acceptance surface (ISSUE-14).

- TTFT decomposes into named segments (admission-wait, prefill,
  KV-handoff, first-token) under ONE trace id, verified by walking the
  dumped Chrome trace (not the in-memory event store);
- per-link byte attribution: two tenants' pulls produce
  {peer, qos_class, owner}-tagged rx/tx totals that match the agent's
  wire accounting within 1%;
- killing a worker mid-collective produces postmortem bundles from the
  VICTIM (synchronously, before os._exit) and from a SURVIVOR (on the
  collective abort), in the configured flight_recorder_dir.
"""

import json
import os
import sys
import time

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import config as cfg
from ray_tpu._private import flight_recorder
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.worker_group import WorkerGroup

# worker subprocesses can't import the tests package: ship helpers by value
cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


# ---------------------------------------------------------------------------
# TTFT decomposition in the dumped Chrome trace
# ---------------------------------------------------------------------------

TTFT_SEGMENTS = {"serve.admission_wait", "serve.prefill",
                 "serve.kv_handoff", "serve.first_token"}


def test_ttft_decomposes_in_dumped_chrome_trace(cluster, tmp_path):
    from ray_tpu.serve.llm_pool import LLMPool

    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=4, prompt_buckets=(8, 16),
                   min_replicas=1, max_replicas=1, prefill_workers=1,
                   prefill_threshold=12, autoscale=False)
    try:
        prompt = np.random.RandomState(4).randint(
            1, 256, size=14).tolist()  # >= threshold: disaggregated
        out = pool.generate(prompt, 8)
        assert len(out["tokens"]) == 8

        dump = tmp_path / "trace.json"
        found = None
        deadline = time.time() + 30
        while time.time() < deadline and found is None:
            flight_recorder.flush_now()
            ray_tpu.timeline(str(dump))
            with open(dump) as f:
                trace = json.load(f)
            by_tid: dict = {}
            for ev in trace:
                if ev.get("cat") != "serve":
                    continue
                tid = ev["args"].get("trace_id")
                if tid:
                    by_tid.setdefault(tid, []).append(ev)
            for tid, evs in by_tid.items():
                if TTFT_SEGMENTS <= {e["name"] for e in evs}:
                    found = evs
                    break
            if found is None:
                time.sleep(0.3)
        assert found is not None, "TTFT segments never joined one trace"

        seg = {e["name"]: e for e in found}
        for name in TTFT_SEGMENTS:
            assert seg[name]["dur"] >= 0.0
        # the decomposition is ordered: admission opens the request,
        # prefill precedes the KV handoff, and the first token lands
        # at/after everything else finishes
        assert seg["serve.admission_wait"]["ts"] <= \
            seg["serve.kv_handoff"]["ts"]
        assert seg["serve.prefill"]["ts"] <= seg["serve.kv_handoff"][
            "ts"] + seg["serve.kv_handoff"]["dur"]
        ft_end = seg["serve.first_token"]["ts"] + \
            seg["serve.first_token"]["dur"]
        assert ft_end >= seg["serve.kv_handoff"]["ts"]
        # the prefill span crosses processes yet stays on this trace
        assert seg["serve.prefill"]["args"]["kv_bytes"] > 0
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# two-tenant byte attribution vs wire accounting
# ---------------------------------------------------------------------------


@pytest.fixture
def agents_cluster():
    # agents only, NO driver connect: drives the agent-to-agent chunk
    # path directly (same idiom as test_data_plane.cluster3)
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30},
                store_capacity=256 * 2**20)
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    yield c
    c.shutdown()


def _seed_owned(cluster, agent, data: bytes, owner_wid: bytes):
    oid = os.urandom(16)
    agent.store.put_bytes(oid, data, metadata=b"")
    cluster.io.run(agent.rpc_object_sealed(
        None, {"object_id": oid, "size": len(data),
               "owner": {"worker_id": owner_wid}}))
    return oid


def test_two_tenant_byte_attribution_matches_wire(agents_cluster):
    from ray_tpu._private import net_accounting as net

    c = agents_cluster
    src, dst = c.agents[0], c.agents[1]
    net.reset_local()

    tenants = {
        "a": (bytes([0xAA]) * 16, 12 * 2**20),
        "b": (bytes([0xBB]) * 16, 6 * 2**20),
    }
    oids = {}
    for t, (wid, size) in tenants.items():
        oids[t] = _seed_owned(c, src, os.urandom(size), wid)

    base = dst.transfer_stats["pull_bytes"]
    for t in tenants:
        assert c.io.run(dst.rpc_fetch_object(
            None, {"object_id": oids[t], "timeout": 60}))
    wire = dst.transfer_stats["pull_bytes"] - base
    assert wire >= sum(size for _, size in tenants.values())

    per_owner_rx = {}
    per_owner_tx = {}
    for t, (wid, size) in tenants.items():
        owner = wid.hex()[:12]
        rx = net.total("rx", qos_class="bulk", owner=owner)
        tx = net.total("tx", qos_class="bulk", owner=owner)
        # each tenant's attributed bytes are exactly its object payload
        assert rx == size, (t, rx, size)
        # serving side accounted symmetrically from the request tags
        assert tx == size, (t, tx, size)
        per_owner_rx[owner] = rx
        per_owner_tx[owner] = tx

    # attribution covers the wire accounting within 1% — nothing moved
    # unattributed, nothing double-counted
    total_rx = sum(per_owner_rx.values())
    assert abs(total_rx - wire) <= 0.01 * wire, (total_rx, wire)


# ---------------------------------------------------------------------------
# mid-collective kill: victim AND survivor postmortems
# ---------------------------------------------------------------------------


def _fr_survivor_allreduce(worker, group):
    from ray_tpu.collective import CollectiveAbortError, allreduce

    try:
        allreduce(np.ones(256, np.float32), group, timeout=60.0)
        return {"aborted": False}
    except CollectiveAbortError:
        return {"aborted": True, "pid": os.getpid()}


def _fr_victim_allreduce(worker, group):
    from ray_tpu._private import fault_injection
    from ray_tpu.collective import allreduce

    fault_injection.configure([{
        "site": "ring.send", "match": {"rank": 1, "step": 0, "chunk": 0},
        "action": "exit",
    }])
    return allreduce(np.ones(256, np.float32), group, timeout=60.0)


def test_mid_collective_kill_dumps_victim_and_survivor(cluster, tmp_path):
    old_dir = cfg.get("flight_recorder_dir")
    cfg.set_system_config({"flight_recorder_dir": str(tmp_path)})
    try:
        wg = WorkerGroup(2, resources_per_worker={"CPU": 1},
                         max_restarts=0)
        try:
            group = wg.init_collective()
            refs = [
                wg.workers[0].execute.remote(_fr_survivor_allreduce,
                                             group),
                wg.workers[1].execute.remote(_fr_victim_allreduce,
                                             group),
            ]
            surv = ray_tpu.get(refs[0], timeout=90)
            assert surv["aborted"], surv

            # victim dumped synchronously before os._exit; the
            # survivor dumped on its abort — wait for both bundles
            deadline = time.time() + 30
            metas = []
            while time.time() < deadline:
                metas = []
                for p in sorted(tmp_path.glob("fr-*.json")):
                    try:
                        with open(p) as f:
                            metas.append(json.load(f)["meta"])
                    except (OSError, ValueError):
                        pass  # mid-write
                reasons = [m["reason"] for m in metas]
                if (any(r.startswith("fault:ring.send") for r in reasons)
                        and any(r.startswith("collective-abort:")
                                for r in reasons)):
                    break
                time.sleep(0.25)
            reasons = {m["reason"]: m for m in metas}
            victim = next((m for r, m in reasons.items()
                           if r.startswith("fault:ring.send")), None)
            survivor = next((m for r, m in reasons.items()
                             if r.startswith("collective-abort:")), None)
            assert victim is not None, sorted(reasons)
            assert survivor is not None, sorted(reasons)
            # two distinct processes: both sides of the failure dumped
            assert victim["pid"] != survivor["pid"]
            assert victim["extra"]["ctx"]["rank"] == 1
            assert survivor["extra"]["reason"], survivor["extra"]
            assert group in next(
                r for r in reasons if r.startswith("collective-abort:"))
        finally:
            wg.shutdown()
    finally:
        cfg.set_system_config({"flight_recorder_dir": old_dir})
