"""Data streaming for real (VERDICT r2 item 8): consumer-side prefetch,
byte-budgeted streaming through a multi-stage pipeline over data larger
than the object store, and ActorPoolMapOperator with per-actor init."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.cluster_utils import Cluster

STORE_CAP = 64 * 1024 * 1024  # 64 MB store


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 8 * 2**30},
                store_capacity=STORE_CAP)
    c.connect()
    yield c
    c.shutdown()


def test_prefetch_iter_batches(cluster):
    ds = rdata.from_items(list(range(1000)), parallelism=8).map_batches(
        lambda b: [x * 2 for x in b])
    plain = [row for b in ds.iter_batches() for row in b]
    pre = [row for b in ds.iter_batches(prefetch_batches=3) for row in b]
    assert pre == plain == [x * 2 for x in range(1000)]


def test_stream_4x_store_capacity_bounded(cluster):
    """3-stage pipeline over ~4x the object store capacity: lazy numpy
    sources fuse into the map tasks and outputs are freed after
    consumption, so store occupancy stays BOUNDED (asserted on the live
    store) while every row flows through."""
    import tempfile

    # 16 files x 16 MB = 256 MB through a 64 MB store
    n_files, rows = 16, 2 * 1024 * 1024  # 2M float64 = 16 MB per file
    d = tempfile.mkdtemp(prefix="ds_stream_")
    for i in range(n_files):
        np.save(os.path.join(d, f"f_{i:02d}.npy"),
                np.full(rows, float(i), np.float64))

    ds = (rdata.read_numpy(os.path.join(d, "*.npy"))
          .map_batches(lambda a: a + 1.0)
          .map_batches(lambda a: a * 2.0))

    store = cluster.head_agent.store
    peak = 0
    total_rows = 0
    checks = []
    for block in ds.streaming_iter_batches(
            byte_budget=STORE_CAP // 2, max_in_flight=3):
        total_rows += len(block)
        checks.append(float(block[0]))
        peak = max(peak, store.used_bytes())
        del block
    assert total_rows == n_files * rows
    assert sorted(checks) == [(i + 1.0) * 2.0 for i in range(n_files)]
    # bounded occupancy: never anywhere near the 256 MB that flowed
    assert peak <= STORE_CAP, f"peak store occupancy {peak}"
    # and after the stream the outputs are freed
    import time

    deadline = time.time() + 20
    while time.time() < deadline and store.used_bytes() > STORE_CAP // 4:
        time.sleep(0.2)
    assert store.used_bytes() <= STORE_CAP // 4

    for f in os.listdir(d):
        os.unlink(os.path.join(d, f))
    os.rmdir(d)


class _Doubler:
    """Callable class for actor compute: counts its constructions."""

    def __init__(self):
        import os as _os

        self.pid = _os.getpid()
        self.calls = 0

    def __call__(self, block):
        self.calls += 1
        return [(x * 2, self.pid) for x in block]


def test_actor_pool_map_with_per_actor_init(cluster):
    ds = rdata.from_items(list(range(120)), parallelism=12)
    out = ds.map_batches(
        _Doubler, compute=rdata.ActorPoolStrategy(size=3))
    rows = [r for b in out.iter_batches() for r in b]
    vals = sorted(v for v, _ in rows)
    assert vals == sorted(x * 2 for x in range(120))
    # exactly `size` distinct actor processes served the 12 blocks
    pids = {pid for _, pid in rows}
    assert len(pids) == 3


def test_actor_pool_composes_with_task_stages(cluster):
    ds = (rdata.from_items(list(range(60)), parallelism=6)
          .map_batches(lambda b: [x + 1 for x in b])
          .map_batches(_Doubler, compute=rdata.ActorPoolStrategy(size=2))
          .map_batches(lambda b: [v for v, _ in b]))
    rows = sorted(r for b in ds.iter_batches() for r in b)
    assert rows == sorted((x + 1) * 2 for x in range(60))


def test_lazy_read_still_supports_eager_consumers(cluster, tmp_path):
    """Lazy sources materialize transparently for non-streaming ops."""
    import pandas as pd

    f = tmp_path / "t.csv"
    pd.DataFrame({"a": [1, 2, 3], "b": [4.0, 5.0, 6.0]}).to_csv(
        f, index=False)
    ds = rdata.read_csv(str(f))
    assert ds.count() == 3
    rows = list(ds.iter_rows())
    assert len(rows) == 3


# ---------------- logical plan + per-operator budgets (r4) ----------------

def test_plan_fusion_and_limit_pushdown_rules(cluster):
    """Unit tests on the optimized logical plan (data/logical.py):
    consecutive task maps fuse; a limit annotates the Read with an
    early-stop hint; stacked limits merge; exchanges are barriers."""
    ds = (rdata.from_items(list(range(100)), parallelism=10)
          .map_batches(lambda b: b)
          .map_batches(lambda b: b)
          .limit(30)
          .limit(50))
    plan = ds.explain()
    assert "FusedMap[2 fns]" in plan, plan
    assert "limit_hint=30" in plan, plan
    assert "Limit[30]" in plan, plan
    assert "FuseMaps" in plan and "LimitPushdown" in plan \
        and "MergeLimits" in plan, plan

    # an exchange blocks pushdown: the hint must NOT cross it
    ds2 = (rdata.from_items(list(range(100)), parallelism=10)
           .random_shuffle(seed=0)
           .limit(5))
    plan2 = ds2.explain()
    assert "limit_hint" not in plan2, plan2
    assert "Exchange[random_shuffle]" in plan2, plan2


def test_limit_pushdown_skips_unneeded_sources(cluster):
    """With the early-stop hint, a limit over lazy sources only ever
    launches the source units it needs."""
    import tempfile

    d = tempfile.mkdtemp(prefix="ds_limit_")
    marker = os.path.join(d, "ran")

    n_rows_per = 10

    def make_source(i):
        def _src(i=i):
            # side-channel: record which sources actually ran
            with open(marker, "a") as f:
                f.write(f"{i}\n")
            return [i * n_rows_per + j for j in range(n_rows_per)]
        return _src

    from ray_tpu._private import serialization
    from ray_tpu.data.dataset import Dataset

    blobs = [serialization.pack_callable(make_source(i))
             for i in range(12)]
    ds = Dataset(_source_blobs=blobs).limit(15)
    rows = [r for b in ds.iter_batches() for r in b]
    assert rows == list(range(15))
    with open(marker) as f:
        ran = sorted(int(x) for x in f.read().split())
    # 15 rows need 2 sources; the in-flight window may overshoot a bit,
    # but nowhere near all 12
    assert len(ran) <= 8, ran  # async probes may lag a window

    for f in os.listdir(d):
        os.unlink(os.path.join(d, f))
    os.rmdir(d)


@pytest.mark.slow  # ~10s; budget window + overlap + actor-pool tests keep tier-1 coverage
def test_budgeted_pipeline_with_shuffle_and_actor_pool(cluster):
    """The round-4 capacity test: lazy sources -> fused map ->
    random_shuffle (push-based exchange) -> actor-pool map, ~3x the
    object store, ALL stages metered by one dataset byte budget
    (reference streaming_executor_state.py per-operator budgets).
    Completion without store errors + row-multiset correctness is the
    bar; the shuffle necessarily materializes its outputs (all-to-all),
    with spill absorbing what exceeds memory."""
    import tempfile

    n_files, rows = 24, 1024 * 1024  # 24 x 8 MB = 192 MB through 64 MB
    d = tempfile.mkdtemp(prefix="ds_budget_")
    for i in range(n_files):
        np.save(os.path.join(d, f"f_{i:02d}.npy"),
                np.full(rows, float(i), np.float64))

    ds = (rdata.read_numpy(os.path.join(d, "*.npy"))
          .map_batches(lambda a: a[: 4096] + 1.0)   # shrink + shift
          .random_shuffle(seed=7)
          .map_batches(lambda b: [float(np.sum(np.asarray(b) > 0))],
                       compute=rdata.ActorPoolStrategy(size=2))
          .with_byte_budget(STORE_CAP // 4))

    plan = ds.explain()
    assert "Exchange[random_shuffle]" in plan and "ActorPoolMap" in plan, \
        plan
    counts = [r for b in ds.iter_batches() for r in b]
    # every row of every (shrunk) block survived the shuffle: the
    # positive-count total equals files x 4096 rows
    assert sum(counts) == n_files * 4096

    for f in os.listdir(d):
        os.unlink(os.path.join(d, f))
    os.rmdir(d)


def test_pipeline_stages_overlap(cluster):
    """Pull-based execution (VERDICT r4 item 2): stage N+1 tasks chain on
    stage N's PENDING refs, so a downstream block starts the moment its
    own upstream block lands — with the old per-stage meter.drain()
    barriers, every stage-2 start waited for the SLOWEST stage-1 block."""
    import time

    def slow_stage1(rows):
        # staggered durations: block i finishes at ~0.15*i
        time.sleep(0.15 * rows[0])
        return [(rows[0], time.time())]  # (block idx, stage1 end ts)

    def stage2(rows):
        idx, t_end1 = rows[0]
        return [(idx, t_end1, time.time())]  # + stage2 start ts

    ds = (rdata.from_items(list(range(6)), parallelism=6)
          .map_batches(slow_stage1)
          # an actor pool breaks task-fusion, making stage2 a real
          # separate operator
          .map_batches(stage2, compute=rdata.ActorPoolStrategy(size=2)))
    rows = [r for b in ds.iter_batches() for r in b]
    assert len(rows) == 6
    latest_stage1_end = max(r[1] for r in rows)
    earliest_stage2_start = min(r[2] for r in rows)
    # block 0's stage2 must start well before block 5's stage1 finishes
    assert earliest_stage2_start < latest_stage1_end - 0.2, (
        f"stages did not overlap: earliest stage2 start "
        f"{earliest_stage2_start:.3f} vs latest stage1 end "
        f"{latest_stage1_end:.3f}")


def test_budget_meter_first_window_bounded(cluster):
    """BudgetMeter must not admit blind before its first observation
    (VERDICT r4 weak 3): with a byte budget set and no sizes observed
    yet, the admission window is 2, not max_in_flight."""
    from ray_tpu.data.logical import BudgetMeter

    m = BudgetMeter(byte_budget=1 << 20, max_in_flight=8)
    assert not m._over()
    m.in_flight = ["a"]
    assert not m._over()
    m.in_flight = ["a", "b"]
    assert m._over()  # 2-wide learn window until a size is observed
    # once sizes are known, the byte budget sizes the window
    m.avg = [2.0 * (1 << 18), 2]  # avg 256KB -> (2+1)*256K < 1MB
    assert not m._over()
    m.in_flight = ["a", "b", "c", "d"]
    assert m._over()              # (4+1)*256K > 1MB
    # no budget: only the in-flight window applies
    m2 = BudgetMeter(byte_budget=None, max_in_flight=4)
    m2.in_flight = ["a", "b", "c"]
    assert not m2._over()
