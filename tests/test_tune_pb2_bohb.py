"""PB2 (GP-bandit PBT) and BOHB (multi-fidelity TPE) — native
model-based search (reference tune/schedulers/pb2.py,
tune/search/bohb/bohb_search.py)."""

import math
import random
import statistics

from ray_tpu import tune
from ray_tpu.tune.schedulers import PB2


def _drive_pbt_like(sched, *, f, n_trials=6, intervals=8, seed=0):
    """Minimal PBT population loop: each trial holds an lr; per interval
    the score is f(lr) + noise; exploit decisions clone+explore exactly
    like the Tuner does. Returns the best score seen."""
    rng = random.Random(seed)
    configs = {f"trial_{i:04d}": {"lr": 10 ** rng.uniform(-5, -1)}
               for i in range(n_trials)}
    for t, c in configs.items():
        sched.on_trial_config(t, c) if hasattr(
            sched, "on_trial_config") else None
    best = float("inf")
    for it in range(1, intervals + 1):
        for t, cfg in list(configs.items()):
            score = f(cfg["lr"]) + rng.gauss(0, 0.01)
            best = min(best, score)
            decision = sched.on_result(t, it, score)
            if isinstance(decision, tuple) and decision[0] == "exploit":
                donor = decision[1]
                new_cfg = sched.explore(dict(configs[donor]))
                configs[t] = new_cfg
                if hasattr(sched, "on_trial_config"):
                    sched.on_trial_config(t, new_cfg)
    return best


def test_pb2_beats_pbt_on_seeded_quadratic():
    """Median best score over seeds: PB2's GP-guided exploration finds
    the optimum lr faster than PBT's random x0.8/x1.2 jitter."""

    def f(lr):
        return (math.log10(lr) + 3.0) ** 2  # optimum at lr=1e-3

    muts = {"lr": tune.loguniform(1e-5, 1e-1)}
    pbt_bests, pb2_bests = [], []
    for seed in range(8):
        pbt = tune.PopulationBasedTraining(
            metric="loss", mode="min", perturbation_interval=2,
            hyperparam_mutations=muts, seed=seed)
        pbt_bests.append(_drive_pbt_like(pbt, f=f, seed=seed))
        pb2 = PB2(metric="loss", mode="min", perturbation_interval=2,
                  hyperparam_mutations=muts, seed=seed)
        pb2_bests.append(_drive_pbt_like(pb2, f=f, seed=seed))
    assert statistics.median(pb2_bests) <= statistics.median(pbt_bests), (
        sorted(pb2_bests), sorted(pbt_bests))


def test_pb2_explore_uses_gp_after_warmup():
    muts = {"lr": tune.loguniform(1e-5, 1e-1)}
    pb2 = PB2(metric="loss", mode="min", perturbation_interval=1,
              hyperparam_mutations=muts, seed=0)
    # feed observations: configs near lr=1e-3 improve a lot, far ones
    # not at all
    for i, lr in enumerate([1e-5, 1e-4, 1e-3, 2e-3, 1e-2, 1e-1]):
        t = f"trial_{i:04d}"
        pb2.on_trial_config(t, {"lr": lr})
        improvement = 1.0 - min(1.0, abs(math.log10(lr) + 3.0))
        pb2.on_result(t, 1, 5.0)              # baseline score
        pb2.on_result(t, 2, 5.0 - improvement)  # delta observed at t=2
    out = pb2.explore({"lr": 1e-5})
    # GP-UCB should move lr toward the productive region, far from the
    # donor's 1e-5 (plain PBT could only reach 0.8e-5..1.2e-5)
    assert out["lr"] > 1e-4, out


def test_bohb_uses_highest_informative_budget():
    space = {"x": tune.uniform(-2.0, 2.0)}
    s = tune.BOHBSearcher(metric="loss", mode="min", n_startup_trials=3,
                          seed=1, min_points_in_model=3)
    s.set_space(space)
    # low-budget model says x≈-1 is good (misleading); high-budget says
    # x≈+1. With enough high-budget points the model must follow them.
    rng = random.Random(0)
    for i in range(12):
        x = rng.uniform(-2, 2)
        s.on_trial_complete(f"lo{i}", {
            "loss": (x + 1.0) ** 2, "config": {"x": x},
            "training_iteration": 1})
    for i in range(8):
        x = rng.uniform(-2, 2)
        s.on_trial_complete(f"hi{i}", {
            "loss": (x - 1.0) ** 2, "config": {"x": x},
            "training_iteration": 9})
    xs = [s.suggest(f"t{i}")["x"] for i in range(16)]
    mean_x = sum(xs) / len(xs)
    assert mean_x > 0.0, xs  # pulled toward the high-budget optimum


def test_bohb_beats_random_on_quadratic():
    def f(x):
        return (x - 0.3) ** 2

    space = {"x": tune.uniform(-2.0, 2.0)}
    random_bests, bohb_bests = [], []
    for seed in range(8):
        rng = random.Random(seed)
        random_bests.append(
            min(f(space["x"].sample(rng)) for _ in range(30)))
        s = tune.BOHBSearcher(metric="loss", mode="min",
                              n_startup_trials=4, seed=seed)
        s.set_space(space)
        best = float("inf")
        for i in range(15):
            cfg = s.suggest(f"t{i}")
            loss = f(cfg["x"])
            best = min(best, loss)
            s.on_trial_complete(f"t{i}", {
                "loss": loss, "config": cfg, "training_iteration": 5})
        bohb_bests.append(best)
    assert statistics.median(bohb_bests) < statistics.median(random_bests), (
        sorted(bohb_bests), sorted(random_bests))
