"""Serve v0 tests: deploy/route/batch + batched jitted llama decode.

Reference analogs: python/ray/serve/tests/test_standalone.py,
test_batching.py, scaled to the handle (HTTP-less) data path.
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    serve.shutdown()
    c.shutdown()


def test_deploy_and_route(cluster):
    @serve.deployment(num_replicas=2, max_concurrent_queries=4)
    class Echo:
        def __init__(self, tag):
            self.tag = tag

        def __call__(self, x):
            import os

            return (self.tag, x, os.getpid())

    h = serve.run(Echo, name="echo", init_args=("v1",))
    outs = ray_tpu.get(
        [h.remote(i) for i in range(20)], timeout=120
    )
    assert all(tag == "v1" and x == i for (tag, x, _), i in
               zip(outs, range(20)))
    # both replicas served traffic
    pids = {pid for (_, _, pid) in outs}
    assert len(pids) == 2


def test_redeploy_new_version(cluster):
    """Rolling redeploy under concurrent load drops ZERO requests.

    Old replicas are drained (unpublished, killed only when idle), so every
    request issued during the roll succeeds — returning the old or the new
    version, never an error."""
    import threading

    @serve.deployment(num_replicas=2)
    class V:
        def __init__(self, v):
            self.v = v

        def __call__(self, _):
            import time as t

            t.sleep(0.02)  # keep requests in flight during the roll
            return self.v

    h = serve.run(V, name="v", init_args=("one",))
    assert ray_tpu.get(h.remote(0), timeout=60) == "one"

    results: list = []
    errors: list = []
    stop = threading.Event()

    def fire():
        while not stop.is_set():
            try:
                results.append(ray_tpu.get(h.remote(0), timeout=60))
            except Exception as e:  # noqa: BLE001 — the assertion target
                errors.append(e)

    threads = [threading.Thread(target=fire) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        h = serve.run(V, name="v", init_args=("two",), version="2")
        # keep firing a moment after the roll completes
        deadline = time.time() + 5
        while time.time() < deadline and "two" not in results[-8:]:
            time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)

    assert not errors, f"requests failed during rolling redeploy: {errors[:3]}"
    assert set(results) <= {"one", "two"}
    assert "two" in results  # the roll completed into the new version
    assert ray_tpu.get(h.remote(0), timeout=60) == "two"


def test_method_call(cluster):
    @serve.deployment(num_replicas=1)
    class M:
        def stats(self):
            return {"ok": True}

    h = serve.run(M, name="m")
    assert ray_tpu.get(h.method("stats").remote(), timeout=60) == {
        "ok": True
    }


def test_batching_groups_requests(cluster):
    @serve.deployment(num_replicas=1, max_concurrent_queries=16)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        def _handle(self, items):
            self.batch_sizes.append(len(items))
            return [i * 10 for i in items]

        def __call__(self, x):
            return self._handle(x)

        def sizes(self):
            return self.batch_sizes

    h = serve.run(Batched, name="batched")
    refs = [h.remote(i) for i in range(16)]
    outs = ray_tpu.get(refs, timeout=120)
    assert sorted(outs) == [i * 10 for i in range(16)]
    sizes = ray_tpu.get(h.method("sizes").remote(), timeout=60)
    # at least one multi-request batch formed
    assert max(sizes) > 1


def test_serve_llama_decode(cluster):
    """Replica hosting tiny-llama with a jitted KV-cache decode path,
    batched requests, p50 latency asserted (VERDICT item 7 'done' bar)."""

    @serve.deployment(num_replicas=1, max_concurrent_queries=16)
    class LM:
        def __init__(self):
            import jax

            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp

            from ray_tpu.models import llama

            self.llama = llama
            self.jnp = jnp
            self.cfg = llama.LlamaConfig.tiny()
            self.params = llama.init_params(
                self.cfg, __import__("jax").random.PRNGKey(0)
            )

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
        def _generate(self, prompts):
            import numpy as np

            arr = self.jnp.asarray(np.stack(prompts))
            out = self.llama.greedy_generate(
                self.params, arr, self.cfg, max_new_tokens=4
            )
            return [np.asarray(o) for o in out]

        def __call__(self, prompt):
            return self._generate(prompt)

    h = serve.run(LM, name="lm")
    prompt = np.array([1, 2, 3, 4], dtype=np.int32)
    # warm (compile)
    first = ray_tpu.get(h.remote(prompt), timeout=300)
    assert first.shape == (8,)
    assert list(first[:4]) == [1, 2, 3, 4]

    lat: list[float] = []

    def one():
        t0 = time.perf_counter()
        out = ray_tpu.get(h.remote(prompt), timeout=120)
        lat.append(time.perf_counter() - t0)
        assert out.shape == (8,)

    threads = [threading.Thread(target=one) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(lat) == 8
    p50 = sorted(lat)[len(lat) // 2]
    assert p50 < 5.0  # CPU tiny-llama, batched: comfortably sub-5s


def test_config_file_deploy(cluster, tmp_path):
    """Declarative deploy from a YAML config (reference serve schema +
    `serve deploy` CLI): import_path resolution, per-deployment overrides,
    and redeploy-by-reapply."""
    import sys
    import textwrap

    mod = tmp_path / "my_service_mod.py"
    mod.write_text(textwrap.dedent("""
        from ray_tpu import serve

        @serve.deployment(num_replicas=1)
        class Greeter:
            def __init__(self, greeting="hi"):
                self.greeting = greeting
                self.punct = ""

            def reconfigure(self, cfg):
                self.punct = cfg.get("punct", "")

            def __call__(self, name):
                return f"{self.greeting} {name}{self.punct}"
    """))
    cfg = tmp_path / "serve.yaml"
    cfg.write_text(textwrap.dedent("""
        applications:
          - name: greeter
            import_path: my_service_mod:Greeter
            route_prefix: /greet
            version: "1"
            init_kwargs:
              greeting: hello
            deployments:
              - name: Greeter
                num_replicas: 2
                max_concurrent_queries: 4
                user_config:
                  punct: "!"
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        from ray_tpu.serve import schema as serve_schema

        names = serve_schema.apply(str(cfg))
        assert names == ["greeter"]
        h = serve.get_handle("greeter")
        assert ray_tpu.get(h.remote("world"), timeout=60) == "hello world!"
        st = serve_schema.status()
        assert st["greeter"]["num_replicas"] == 2

        # re-apply with a new version: rolling redeploy via config
        cfg.write_text(cfg.read_text().replace('version: "1"',
                                               'version: "2"')
                       .replace("greeting: hello", "greeting: hey"))
        serve_schema.apply(str(cfg))
        assert ray_tpu.get(h.remote("you"), timeout=60) == "hey you!"

        # malformed config rejected
        import pytest as _pytest

        with _pytest.raises(ValueError):
            serve_schema.apply({"applications": [{"name": "x"}]})
    finally:
        sys.path.remove(str(tmp_path))


def test_serve_rest_api(cluster, tmp_path):
    """Declarative serve over the dashboard REST endpoint (reference
    dashboard/modules/serve): PUT /api/serve/applications applies a
    config document; GET returns running deployments."""
    import http.client
    import json
    import sys
    import textwrap

    from ray_tpu.dashboard import start_dashboard

    mod = tmp_path / "rest_service_mod.py"
    mod.write_text(textwrap.dedent("""
        from ray_tpu import serve

        @serve.deployment(num_replicas=1)
        class Adder:
            def __call__(self, x):
                return x + 100
    """))
    sys.path.insert(0, str(tmp_path))
    try:
        host, port = start_dashboard()
        conn = http.client.HTTPConnection(host, port, timeout=120)
        body = json.dumps({"applications": [{
            "name": "adder",
            "import_path": "rest_service_mod:Adder",
            "route_prefix": "/adder",
        }]})
        conn.request("PUT", "/api/serve/applications", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200, out
        assert out["deployed"] == ["adder"]

        conn.request("GET", "/api/serve/applications")
        resp = conn.getresponse()
        status = json.loads(resp.read())
        assert resp.status == 200
        assert status["adder"]["num_replicas"] == 1
        conn.close()

        h = serve.get_handle("adder")
        assert ray_tpu.get(h.remote(1), timeout=60) == 101
    finally:
        sys.path.remove(str(tmp_path))
