"""Distributed LearnerGroup (VERDICT r2 item 6): gradient parity vs the
single-process Learner, lockstep replica consistency, wall-clock scaling,
and PPO end-to-end with num_learners > 1."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.rl.learner import Learner, normalize_advantages


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 8 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _fake_batch(n, obs_dim=4, n_actions=2, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "obs": rng.randn(n, obs_dim).astype(np.float32),
        "actions": rng.randint(0, n_actions, n).astype(np.int32),
        "logp": (-np.abs(rng.randn(n))).astype(np.float32),
        "advantages": rng.randn(n).astype(np.float32),
        "returns": rng.randn(n).astype(np.float32),
    }


def test_gradient_parity_vs_single_learner(cluster):
    """2 learners, each on half the batch, mean-allreduced gradients:
    the resulting params must match the single learner's full-batch
    update (minibatches=1 so minibatch membership is identical)."""
    from ray_tpu.rl.learner_group import LearnerGroup

    batch = _fake_batch(64)

    solo = Learner(4, 2, seed=0)
    solo.update(dict(batch), minibatches=1, epochs=3)

    group = LearnerGroup(4, 2, num_learners=2, seed=0)
    try:
        group.update(dict(batch), minibatches=1, epochs=3)
        w_solo = solo.get_weights()
        w_group = group.get_weights()
        flat_a = np.concatenate(
            [np.asarray(x).ravel() for x in _leaves(w_solo)])
        flat_b = np.concatenate(
            [np.asarray(x).ravel() for x in _leaves(w_group)])
        np.testing.assert_allclose(flat_a, flat_b, rtol=1e-4, atol=1e-5)
    finally:
        group.shutdown()


@pytest.mark.slow  # parity stays tier-1 via the even-shard
# test_gradient_parity_vs_single_learner + test_replicas_stay_identical
def test_gradient_parity_unequal_shards(cluster):
    """n=65 across 2 learners (33/32 split): row-weighted allreduce must
    still equal the single learner's full-batch update."""
    from ray_tpu.rl.learner_group import LearnerGroup

    batch = _fake_batch(65, seed=5)
    solo = Learner(4, 2, seed=0)
    solo.update(dict(batch), minibatches=1, epochs=2)
    group = LearnerGroup(4, 2, num_learners=2, seed=0)
    try:
        group.update(dict(batch), minibatches=1, epochs=2)
        flat_a = np.concatenate(
            [np.asarray(x).ravel() for x in _leaves(solo.get_weights())])
        flat_b = np.concatenate(
            [np.asarray(x).ravel() for x in _leaves(group.get_weights())])
        np.testing.assert_allclose(flat_a, flat_b, rtol=1e-4, atol=1e-5)
    finally:
        group.shutdown()


def test_replicas_stay_identical(cluster):
    """After sharded multi-minibatch updates, every replica holds the
    SAME params (the allreduce is the only thing keeping them in sync)."""
    from ray_tpu.rl.learner_group import LearnerGroup

    group = LearnerGroup(4, 2, num_learners=2, seed=0)
    try:
        group.update(_fake_batch(96, seed=1), minibatches=3, epochs=2)
        w = [ray_tpu.get(a.get_weights.remote(), timeout=120)
             for a in group.learners]
        f0 = np.concatenate([np.asarray(x).ravel() for x in _leaves(w[0])])
        f1 = np.concatenate([np.asarray(x).ravel() for x in _leaves(w[1])])
        np.testing.assert_allclose(f0, f1, rtol=1e-6, atol=1e-7)
    finally:
        group.shutdown()


@pytest.mark.slow  # ~24s scaling sweep; gradient-parity tests above
def test_scaling_2_and_4_learners(cluster):  # cover the update path
    """Sharded update wall-clock with 2 and 4 learners on a large batch:
    both complete and produce finite metrics; 4-learner shards are half
    the per-actor work of 2-learner shards (asserted via timing being in
    the same ballpark or better — CPU-mesh scaling is about correctness
    under concurrency, not MXU throughput)."""
    from ray_tpu.rl.learner_group import LearnerGroup

    batch = _fake_batch(4096, seed=2)
    times = {}
    for n in (2, 4):
        group = LearnerGroup(4, 2, num_learners=n, seed=0)
        try:
            group.update(dict(batch), minibatches=2, epochs=1)  # warmup
            t0 = time.perf_counter()
            m = group.update(dict(batch), minibatches=2, epochs=4)
            times[n] = time.perf_counter() - t0
            assert np.isfinite(m["total_loss"])
        finally:
            group.shutdown()
    # 4 learners must not be pathologically slower than 2 (lockstep
    # collectives working, no serialization collapse). The bound is
    # deliberately loose: under full-suite CPU contention on an 8-core
    # box, 4 learner actors time-slice against other suites' workers —
    # a tight ratio here measures the machine, not the group.
    assert times[4] < times[2] * 3.5, times
    assert times[4] < 90.0, times  # absolute sanity: no hang/collapse


@pytest.mark.slow  # ~19s; gradient-parity + replica-identity tests above are tier-1
def test_ppo_with_learner_group(cluster):
    """PPO end-to-end with num_learners=2 learns CartPole-ish dynamics
    (the same toy env the single-learner PPO test uses)."""
    from ray_tpu.rl.ppo import PPOConfig
    from tests.test_rl import Corridor  # reuse the suite's env

    algo = PPOConfig(
        env_creator=Corridor,
        obs_dim=2, n_actions=2, num_env_runners=2, rollout_steps=64,
        num_learners=2, sgd_minibatches=2, sgd_epochs=2,
    ).build()
    try:
        first = algo.train()
        for _ in range(3):
            last = algo.train()
        assert last["training_iteration"] == 4
        assert np.isfinite(last["total_loss"])
        assert "episode_return_mean" in last
    finally:
        algo.stop()


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)
