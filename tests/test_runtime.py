"""Task/actor/object runtime tests.

Mirrors the reference's core API tests (python/ray/tests/test_basic*.py,
test_actor*.py, test_object_*.py) against the cluster_utils fixture.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@ray_tpu.remote
def _add(a, b):
    return a + b


@ray_tpu.remote
def _echo(x):
    return x


def test_task_roundtrip(cluster):
    assert ray_tpu.get(_add.remote(1, 2)) == 3


def test_chained_refs(cluster):
    r1 = _add.remote(1, 2)
    r2 = _add.remote(r1, 10)
    r3 = _add.remote(r2, r1)
    assert ray_tpu.get(r3) == 16


def test_parallel_tasks(cluster):
    refs = [_add.remote(i, i) for i in range(40)]
    assert sum(ray_tpu.get(refs)) == sum(2 * i for i in range(40))


def test_large_objects_plasma(cluster):
    arr = np.arange(500_000, dtype=np.float64)
    ref = _echo.remote(arr)
    out = ray_tpu.get(ref)
    np.testing.assert_array_equal(out, arr)


def test_put_get(cluster):
    small = ray_tpu.put(42)
    big = ray_tpu.put(np.ones(300_000))
    assert ray_tpu.get(small) == 42
    assert ray_tpu.get(big).sum() == 300_000


def test_put_ref_as_arg(cluster):
    ref = ray_tpu.put(7)
    assert ray_tpu.get(_add.remote(ref, 1)) == 8


def test_num_returns(cluster):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(cluster):
    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("kaboom")

    with pytest.raises(ValueError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_nested_tasks(cluster):
    @ray_tpu.remote
    def outer(n):
        refs = [_add.remote(i, 1) for i in range(n)]
        return sum(ray_tpu.get(refs))

    assert ray_tpu.get(outer.remote(4)) == 4 + sum(range(4))


def test_wait(cluster):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    fast = slow.remote(0.05)
    never = slow.remote(30)
    ready, pending = ray_tpu.wait([fast, never], num_returns=1, timeout=10)
    assert ready == [fast] and pending == [never]
    ray_tpu.cancel(never, force=True)


def test_get_timeout(cluster):
    @ray_tpu.remote
    def sleepy():
        time.sleep(30)

    ref = sleepy.remote()
    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(ref, timeout=0.2)
    ray_tpu.cancel(ref, force=True)


def test_actor_basics(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def incr(self, by=1):
            self.v += by
            return self.v

    c = Counter.remote(10)
    assert ray_tpu.get(c.incr.remote()) == 11
    assert ray_tpu.get(c.incr.remote(5)) == 16


def test_actor_call_ordering(cluster):
    @ray_tpu.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)
            return len(self.items)

        def get(self):
            return self.items

    a = Appender.remote()
    for i in range(50):
        a.add.remote(i)
    assert ray_tpu.get(a.get.remote()) == list(range(50))


def test_named_actor_and_get_actor(cluster):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    s = Store.options(name="kvstore").remote()
    ray_tpu.get(s.set.remote("a", 1))
    again = ray_tpu.get_actor("kvstore")
    assert ray_tpu.get(again.get.remote("a")) == 1
    with pytest.raises(Exception):
        Store.options(name="kvstore").remote()  # name taken


def test_actor_handle_passing(cluster):
    @ray_tpu.remote
    class Sink:
        def __init__(self):
            self.total = 0

        def add(self, x):
            self.total += x
            return self.total

    @ray_tpu.remote
    def feed(sink, n):
        return ray_tpu.get(sink.add.remote(n))

    sink = Sink.remote()
    refs = [feed.remote(sink, i) for i in range(5)]
    ray_tpu.get(refs)
    assert ray_tpu.get(sink.add.remote(0)) == sum(range(5))


def test_actor_kill(cluster):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote()) == "pong"
    ray_tpu.kill(v)
    time.sleep(0.3)
    with pytest.raises(ray_tpu.RayActorError):
        ray_tpu.get(v.ping.remote(), timeout=10)


def test_actor_restart(cluster):
    @ray_tpu.remote
    class Phoenix:
        def pid(self):
            return os.getpid()

        def die(self):
            os._exit(1)

    p = Phoenix.options(max_restarts=2).remote()
    pid1 = ray_tpu.get(p.pid.remote())
    p.die.remote()
    deadline = time.time() + 30
    pid2 = None
    while time.time() < deadline:
        try:
            pid2 = ray_tpu.get(p.pid.remote(), timeout=10)
            break
        except (ray_tpu.RayActorError, ray_tpu.GetTimeoutError):
            # calls race the death notification; keep retrying until the
            # restarted incarnation answers
            time.sleep(0.2)
    assert pid2 is not None and pid2 != pid1


def test_task_retry_on_worker_death(cluster):
    marker = f"/tmp/rt_retry_{os.getpid()}_{os.urandom(3).hex()}"

    @ray_tpu.remote(max_retries=2)
    def die_once(path):
        if not os.path.exists(path):
            open(path, "w").close()
            os._exit(1)  # simulate worker crash mid-task
        return "survived"

    assert ray_tpu.get(die_once.remote(marker), timeout=60) == "survived"
    os.unlink(marker)


def test_detached_actor_outlives_job(cluster):
    @ray_tpu.remote
    class D:
        def ping(self):
            return 1

    d = D.options(name="detachedx", lifetime="detached").remote()
    assert ray_tpu.get(d.ping.remote()) == 1
    # detached actors survive; killing cleans up
    ray_tpu.kill(d)


def test_cluster_resources(cluster):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU", 0) >= 4


def test_config_flags_env_override():
    from ray_tpu._private import config as cfg

    assert cfg.get("task_spill_max_forwards") == 2
    flags = cfg.all_flags()
    assert "heartbeat_timeout_s" in flags
    import pytest as _pytest

    with _pytest.raises(KeyError):
        cfg.get("not_a_flag")


def test_memory_monitor_kills_newest_task_worker(cluster):
    """OOM policy: the newest task worker dies; max_retries reruns the
    task (reference worker_killing_policy retriable-FIFO)."""
    import asyncio

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def slowish():
        import time as _t

        _t.sleep(8)
        return "done"

    ref = slowish.remote()
    agent = cluster.head_agent
    deadline = time.time() + 30
    while time.time() < deadline:
        # pool tasks track in pool_inflight (busy_task is only the
        # lease/reservation marker since dispatch pipelining)
        if any(w.busy_task or w.pool_inflight
               for w in agent.workers.values()):
            break
        time.sleep(0.1)
    fut = asyncio.run_coroutine_threadsafe(
        agent._oom_kill_once(), cluster.io.loop
    )
    assert fut.result(timeout=10) is True
    # retried on a fresh worker and completes
    assert ray_tpu.get(ref, timeout=60) == "done"


def test_task_events_and_timeline(cluster):
    """Task lifecycle events reach the head store; ray_tpu.timeline()
    renders chrome-trace events (reference gcs_task_manager.h:61 +
    profiling.py:123)."""

    @ray_tpu.remote
    def traced(x):
        return x + 1

    ray_tpu.get([traced.remote(i) for i in range(3)], timeout=60)
    deadline = time.time() + 15
    names = []
    while time.time() < deadline:
        events = ray_tpu.list_tasks()
        names = [e["name"] for e in events if e["name"] == "traced"]
        if len(names) >= 3:
            break
        time.sleep(0.2)
    assert len(names) >= 3
    trace = ray_tpu.timeline()
    spans = [t for t in trace if t["name"] == "traced"]
    assert len(spans) >= 3
    assert all(t["ph"] == "X" and t["dur"] >= 0 for t in spans)


def test_list_objects_state_api(cluster):
    ref = ray_tpu.put(np.arange(200_000))  # plasma-sized
    deadline = time.time() + 10
    found = False
    while time.time() < deadline and not found:
        objs = ray_tpu.list_objects()
        found = any(o["object_id"] == ref.binary() for o in objs)
        time.sleep(0.1)
    assert found
    entry = next(o for o in ray_tpu.list_objects()
                 if o["object_id"] == ref.binary())
    assert entry["num_refs"] >= 1 and entry["locations"]


def test_runtime_env_vars_and_worker_isolation(cluster):
    """runtime_env env_vars reach the worker process; different envs get
    different worker processes (reference runtime_env + worker_pool
    env-hash keying)."""

    @ray_tpu.remote(runtime_env={"env_vars": {"MY_FLAG": "abc"}})
    def read_flag():
        import os as _os

        return (_os.environ.get("MY_FLAG"), _os.getpid())

    @ray_tpu.remote
    def plain():
        import os as _os

        return (_os.environ.get("MY_FLAG"), _os.getpid())

    flag, pid_env = ray_tpu.get(read_flag.remote(), timeout=60)
    none_flag, pid_plain = ray_tpu.get(plain.remote(), timeout=60)
    assert flag == "abc"
    assert none_flag is None
    assert pid_env != pid_plain  # env mismatch forced a separate worker


def test_runtime_env_working_dir(cluster, tmp_path):
    mod = tmp_path / "my_dyn_mod.py"
    mod.write_text("VALUE = 41\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def use_mod():
        import my_dyn_mod

        return my_dyn_mod.VALUE + 1

    assert ray_tpu.get(use_mod.remote(), timeout=60) == 42


def test_runtime_env_actor(cluster):
    @ray_tpu.remote(num_cpus=0, runtime_env={"env_vars": {"A_FLAG": "on"}})
    class EnvActor:
        def flag(self):
            import os as _os

            return _os.environ.get("A_FLAG")

    a = EnvActor.remote()
    assert ray_tpu.get(a.flag.remote(), timeout=60) == "on"
    ray_tpu.kill(a)


def test_user_profile_spans(cluster):
    """util.profiling.profile spans from inside tasks land in the event
    store and render as 'user_span' rows in timeline() (reference
    ProfileEvent / ray.util.tracing analog)."""

    @ray_tpu.remote
    def annotated():
        import time as t

        from ray_tpu.util.profiling import profile

        with profile("phase_one", extra={"k": 1}):
            t.sleep(0.05)
        with profile("phase_two"):
            t.sleep(0.02)
        return 1

    assert ray_tpu.get(annotated.remote(), timeout=60) == 1
    deadline = time.time() + 15
    span_names = set()
    while time.time() < deadline:
        events = ray_tpu.list_tasks()
        span_names = {e["name"] for e in events
                      if e.get("state") == "PROFILE"}
        if {"phase_one", "phase_two"} <= span_names:
            break
        time.sleep(0.2)
    assert {"phase_one", "phase_two"} <= span_names
    trace = ray_tpu.timeline()
    user = [t for t in trace if t["cat"] == "user_span"]
    assert any(t["name"] == "phase_one" and t["dur"] >= 40_000
               for t in user)  # >= 40ms in trace microseconds


def test_cluster_events_recorded(cluster):
    """Structured cluster events carry node lifecycle entries
    (reference dashboard/modules/event)."""
    from ray_tpu._private.api import _get_worker

    events = _get_worker().head.call("list_events", {"limit": 100})
    kinds = {e["kind"] for e in events}
    assert "NODE_ADDED" in kinds
    assert all("ts" in e and "message" in e for e in events)


def test_op_stats_exposed(cluster):
    """Per-route RPC handler stats (asio event-stats analog)."""
    from ray_tpu._private.api import _get_worker

    stats = _get_worker().head.call("op_stats", {})
    methods = {s["method"] for s in stats}
    assert "heartbeat" in methods
    hb = next(s for s in stats if s["method"] == "heartbeat")
    assert hb["count"] > 0 and hb["mean_ms"] >= 0
