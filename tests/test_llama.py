"""Llama model tests: shapes, causality, loss decreases, scan==unrolled."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.models import llama


def _cfg(**kw):
    return llama.LlamaConfig.tiny(**kw)


def test_forward_shapes(rng):
    cfg = _cfg()
    params = llama.init_params(cfg, rng)
    tokens = jax.random.randint(rng, (2, 16), 0, cfg.vocab_size)
    logits = llama.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_param_axes_match_structure(rng):
    cfg = _cfg()
    params = llama.init_params(cfg, rng)
    axes = llama.param_logical_axes(cfg)
    ps = jax.tree_util.tree_structure(params)
    as_ = jax.tree_util.tree_structure(
        axes, is_leaf=lambda x: isinstance(x, tuple) and all(
            isinstance(a, (str, type(None))) for a in x)
    )
    assert ps == as_
    # Every axes tuple rank matches param rank.
    flat_p = jax.tree_util.tree_leaves(params)
    flat_a = jax.tree_util.tree_leaves(
        axes, is_leaf=lambda x: isinstance(x, tuple))
    for p, a in zip(flat_p, flat_a):
        assert p.ndim == len(a), (p.shape, a)


def test_causality(rng):
    cfg = _cfg()
    params = llama.init_params(cfg, rng)
    tokens = jax.random.randint(rng, (1, 16), 0, cfg.vocab_size)
    logits1 = llama.forward(params, tokens, cfg)
    tokens2 = tokens.at[0, 10:].set(0)
    logits2 = llama.forward(params, tokens2, cfg)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), atol=1e-5
    )


def test_loss_decreases_under_sgd(rng):
    cfg = _cfg()
    params = llama.init_params(cfg, rng)
    tokens = jax.random.randint(rng, (4, 33), 0, cfg.vocab_size)
    batch = {"tokens": tokens}
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        (loss, _), grads = jax.value_and_grad(
            llama.loss_fn, has_aux=True)(params, batch, cfg)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_num_params_matches(rng):
    from ray_tpu.utils import tree_num_params

    cfg = _cfg()
    params = llama.init_params(cfg, rng)
    assert tree_num_params(params) == cfg.num_params()


def test_moe_forward_and_loss_decreases(rng):
    """MoE MLP (dense-dispatch, expert axis): forward shapes + learning."""
    cfg = llama.llama2_size("moe-tiny")
    params = llama.init_params(cfg, rng)
    tokens = jax.random.randint(rng, (4, 33), 0, cfg.vocab_size)
    batch = {"tokens": tokens}
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        (loss, _), grads = jax.value_and_grad(
            lambda p: llama.loss_fn(p, batch, cfg), has_aux=True
        )(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(12):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


def test_moe_top_k_masks_experts(rng):
    """top_k must zero all but k experts' gates, and rows renormalize."""
    cfg = llama.llama2_size("moe-tiny")
    for k in (1, 2):
        kcfg = llama.LlamaConfig(**{**cfg.__dict__, "top_k": k})
        params = llama.init_params(kcfg, rng)
        x = jax.random.normal(rng, (2, 16, kcfg.d_model), jnp.float32)
        gates = llama.moe_gates(kcfg, params["layers"]["router"][0], x)
        nonzero = (np.asarray(gates) > 0).sum(axis=-1)
        assert (nonzero == k).all()
        np.testing.assert_allclose(
            np.asarray(gates).sum(-1), 1.0, atol=1e-5
        )


def test_remat_policies_agree(rng):
    """dots vs dots_flash vs nothing: same gradients, different remat."""
    tokens = jax.random.randint(rng, (2, 33), 0, 256)
    batch = {"tokens": tokens}
    grads = {}
    for policy in ("dots", "dots_flash", "dots_flash_qkv",
                   "dots_flash_qkv_mlp", "nothing"):
        # use_flash=True: the flash kernel (interpret mode on CPU) must be
        # in the graph or the flash_out/flash_lse plumbing goes untested
        cfg = llama.LlamaConfig.tiny(
            remat=True, remat_policy=policy, use_flash=True,
            max_seq_len=32,
        )
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        g = jax.grad(lambda p: llama.loss_fn(p, batch, cfg)[0])(params)
        grads[policy] = g
    for policy in ("dots_flash", "nothing"):
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
            ),
            grads["dots"], grads[policy],
        )


def test_generate_scan_matches_eager_greedy(rng):
    """The one-jit scanned decode loop (bench/serve path) must produce
    exactly the eager per-token greedy loop's tokens."""
    cfg = _cfg()
    params = llama.init_params(cfg, rng)
    prompt = jax.random.randint(rng, (2, 7), 0, cfg.vocab_size,
                                dtype=jnp.int32)
    n_new = 9
    eager = llama.greedy_generate(params, prompt, cfg, n_new)
    cache = llama.init_cache(cfg, 2, prompt.shape[1] + n_new)
    scanned, cache2 = llama.generate_scan(params, prompt, cfg, n_new, cache)
    np.testing.assert_array_equal(np.asarray(eager[:, prompt.shape[1]:]),
                                  np.asarray(scanned))
    # the final sampled token is returned but never fed back through
    assert int(cache2["pos"]) == prompt.shape[1] + n_new - 1
