"""Ulysses all-to-all sequence parallelism: parity vs dense attention.

Design-new component (SURVEY §5 — the reference has no SP); pinned
against ops.attention_reference on the virtual CPU mesh like
tests/test_ring_attention.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import attention_reference
from ray_tpu.ops.ulysses import ulysses_attention
from ray_tpu.parallel import MeshConfig, build_mesh, use_mesh

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _qkv(b=2, t=64, h=8, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (b, t, h, d), jnp.float32)  # noqa
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference(causal):
    q, k, v = _qkv()
    ref = attention_reference(q, k, v, causal=causal)
    mesh = build_mesh(MeshConfig(sp=4, tp=2), jax.devices()[:8])
    with use_mesh(mesh):
        out = jax.jit(
            lambda a, b, c: ulysses_attention(a, b, c, causal=causal)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_grads_match():
    q, k, v = _qkv(t=32, seed=3)

    def loss_ref(q_, k_, v_):
        return jnp.sum(attention_reference(q_, k_, v_, causal=True) ** 2)

    def loss_uly(q_, k_, v_):
        return jnp.sum(ulysses_attention(q_, k_, v_, causal=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    mesh = build_mesh(MeshConfig(sp=8), jax.devices()[:8])
    with use_mesh(mesh):
        g_uly = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_uly):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_single_device_fallback():
    q, k, v = _qkv(t=32)
    out = ulysses_attention(q, k, v, causal=True)  # no mesh -> plain path
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_rejects_indivisible_heads():
    q, k, v = _qkv(h=6)
    mesh = build_mesh(MeshConfig(sp=4, tp=2), jax.devices()[:8])
    with use_mesh(mesh):
        with pytest.raises(ValueError, match="not divisible"):
            jax.jit(lambda a, b, c: ulysses_attention(a, b, c))(q, k, v)
