"""Pull prioritization + store-pressure admission (reference
src/ray/object_manager/pull_manager.h:52)."""

import asyncio
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import pull_manager as pm
from ray_tpu.cluster_utils import Cluster


class FakeStore:
    def __init__(self, capacity=1000):
        self._cap = capacity
        self.used = 0

    def used_bytes(self):
        return self.used

    def capacity(self):
        return self._cap


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_priority_order_and_escalation():
    """Task-arg pulls activate before earlier-queued restores; a hot
    duplicate escalates a queued restore."""
    order = []
    gate = None  # first pull blocks until we release it

    async def main():
        nonlocal gate
        gate = asyncio.Event()

        async def pull(oid, deadline, reserve):
            order.append(oid)
            if oid == b"hold":
                await gate.wait()
            return True

        s = pm.PullScheduler(pull, FakeStore(), max_active=1)
        first = s.request(b"hold", pm.PRI_GET, 10)  # occupies the slot
        await asyncio.sleep(0.05)
        r_restore = s.request(b"restore", pm.PRI_RESTORE, 10)
        r_restore2 = s.request(b"restore2", pm.PRI_RESTORE, 10)
        r_arg = s.request(b"arg", pm.PRI_TASK_ARG, 10)  # queued LAST
        s.request(b"restore2", pm.PRI_TASK_ARG, 10)     # escalate
        await asyncio.sleep(0.05)
        gate.set()
        assert await asyncio.wait_for(r_arg, 5)
        assert await asyncio.wait_for(r_restore, 5)
        assert await asyncio.wait_for(r_restore2, 5)
        assert await asyncio.wait_for(first, 5)

    _run(main())
    assert order[0] == b"hold"
    # hottest first once the slot frees: arg and the escalated restore2
    # both run before the plain restore
    assert order.index(b"arg") < order.index(b"restore")
    assert order.index(b"restore2") < order.index(b"restore")


def test_admission_gates_on_headroom():
    """With the store above the watermark, only ONE pull is admitted at
    a time (forward progress), not the full max_active fan-out."""
    store = FakeStore(capacity=1000)
    store.used = 900  # above the 0.8 watermark
    concurrent = []
    peak = []

    async def main():
        async def pull(oid, deadline, reserve):
            reserve(100)
            concurrent.append(oid)
            peak.append(len([1 for _ in concurrent]))
            await asyncio.sleep(0.05)
            concurrent.remove(oid)
            return True

        s = pm.PullScheduler(pull, store, max_active=8)
        futs = [s.request(bytes([i]) * 4, pm.PRI_GET, 10)
                for i in range(6)]
        assert all(await asyncio.wait_for(asyncio.gather(*futs), 10))

    _run(main())
    assert max(peak) == 1  # serialized under pressure


def test_admission_fans_out_with_headroom():
    store = FakeStore(capacity=10_000)
    active = []
    peak = []

    async def main():
        async def pull(oid, deadline, reserve):
            reserve(10)
            active.append(oid)
            peak.append(len(active))
            await asyncio.sleep(0.05)
            active.remove(oid)
            return True

        s = pm.PullScheduler(pull, store, max_active=4)
        futs = [s.request(bytes([i]) * 4, pm.PRI_GET, 10)
                for i in range(8)]
        assert all(await asyncio.wait_for(asyncio.gather(*futs), 10))

    _run(main())
    assert max(peak) == 4  # capped by max_active, not serialized


def test_dedup_and_timeout():
    async def main():
        calls = []

        async def pull(oid, deadline, reserve):
            calls.append(oid)
            await asyncio.sleep(0.2)
            return True

        s = pm.PullScheduler(pull, FakeStore(), max_active=2)
        a = s.request(b"x", pm.PRI_GET, 10)
        b = s.request(b"x", pm.PRI_GET, 10)
        assert a is b  # shared future
        assert await asyncio.wait_for(a, 5)
        assert calls == [b"x"]
        # an expired queued request resolves False, doesn't hang
        blocker_gate = asyncio.Event()

        async def slow_pull(oid, deadline, reserve):
            await blocker_gate.wait()
            return True

        s2 = pm.PullScheduler(slow_pull, FakeStore(), max_active=1)
        s2.request(b"b1", pm.PRI_GET, 30)
        s2.request(b"b2", pm.PRI_GET, 30)  # fills queue behind b1
        doomed = s2.request(b"late", pm.PRI_RESTORE, 0.1)
        assert (await asyncio.wait_for(doomed, 5)) is False
        blocker_gate.set()

    _run(main())


def test_pulls_exceeding_capacity_make_progress():
    """E2E chaos-under-pressure: a node pulls a working set LARGER than
    its store; admission + LRU eviction keep every task completing
    instead of OOM-killing the store."""
    cap = 32 * 1024 * 1024
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30},
                store_capacity=cap)
    c.connect()
    second = c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    try:
        # 12 x 4MB objects created on the head node = 48MB > 32MB store
        blobs = [ray_tpu.put(np.full(1024 * 1024, i, np.float32))
                 for i in range(12)]

        @ray_tpu.remote(num_cpus=2)
        def consume(x, i):
            return float(x[0]) == float(i) and x.nbytes == 4 * 1024 * 1024

        # num_cpus=2 forces spillback spread; every dep must be pulled
        # to whichever node runs the task
        out = ray_tpu.get(
            [consume.remote(b, i) for i, b in enumerate(blobs)],
            timeout=300,
        )
        assert all(out), out
        assert second.store.used_bytes() <= cap
    finally:
        c.shutdown()


def test_per_request_pull_fn_override():
    """A restore rides the same scheduler with its OWN transfer fn
    (disk reload, not a peer pull) — the per-request override the
    PRI_RESTORE routing in node_agent uses."""
    ran = []

    async def main():
        async def default_pull(oid, deadline, reserve):
            ran.append(("default", oid))
            return True

        async def restore_pull(oid, deadline, reserve):
            ran.append(("restore", oid))
            return True

        s = pm.PullScheduler(default_pull, FakeStore(), max_active=2)
        f1 = s.request(b"a", pm.PRI_GET, 10)
        f2 = s.request(b"b", pm.PRI_RESTORE, 10, pull_fn=restore_pull)
        assert await f1 and await f2

    _run(main())
    assert ("default", b"a") in ran
    assert ("restore", b"b") in ran


def test_task_arg_preempts_restore_under_saturated_store():
    """The r4 gap: restores must enter admission at PRI_RESTORE and a
    task-arg pull queued LATER must activate first once a slot frees
    (the class the reference deprioritizes, pull_manager.h:52)."""
    order = []

    async def main():
        gate = asyncio.Event()

        async def pull(oid, deadline, reserve):
            order.append(oid)
            if oid == b"hold":
                await gate.wait()
            return True

        s = pm.PullScheduler(pull, FakeStore(), max_active=1)
        hold = s.request(b"hold", pm.PRI_GET, 10)
        await asyncio.sleep(0.05)
        restore = s.request(b"spilled", pm.PRI_RESTORE, 10)
        await asyncio.sleep(0.02)
        # queued AFTER the restore, must run BEFORE it
        task_arg = s.request(b"dep", pm.PRI_TASK_ARG, 10)
        await asyncio.sleep(0.02)
        gate.set()
        assert await hold and await task_arg and await restore

    _run(main())
    assert order == [b"hold", b"dep", b"spilled"]


def test_outbound_transfer_pacing_backpressure():
    """Sender-side window (reference push_manager.h:29 analog): chunk
    serving to a peer whose connection write buffer is over the window
    WAITS until the buffer recedes; an unblocked peer serves
    immediately."""
    from ray_tpu._private import config as _cfg

    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    try:
        agent = c.head_agent
        window = int(_cfg.get("transfer_outbound_window_bytes"))

        class FakeTransport:
            def __init__(self):
                self.buffered = 0
                self.limits = None

            def get_write_buffer_size(self):
                return self.buffered

            def set_write_buffer_limits(self, high=None, low=None):
                self.limits = (high, low)

        class FakeWriter:
            def __init__(self, t):
                self.transport = t

        class FakeConn:
            def __init__(self, t):
                self.writer = FakeWriter(t)
                self.peer = ("10.0.0.9", 1234)
                self.state = {}

            async def drain(self):
                # transport-wakeup analog of the real ServerConn.drain:
                # resolves once the buffer recedes under the low mark
                low = (self.writer.transport.limits or (None, 0))[1] or 0
                while self.writer.transport.buffered > low:
                    await asyncio.sleep(0.005)

        slow = FakeTransport()
        slow.buffered = window + 1  # receiver backed up
        fast = FakeTransport()

        agent._read_object_chunk = lambda p, conn=None: {"served": True}

        async def scenario():
            t0 = time.monotonic()
            fast_r = await agent.rpc_read_object_chunk(
                FakeConn(fast), {"object_id": b"x" * 16, "offset": 0})
            fast_dt = time.monotonic() - t0

            blocked = asyncio.ensure_future(agent.rpc_read_object_chunk(
                FakeConn(slow), {"object_id": b"x" * 16, "offset": 0}))
            await asyncio.sleep(0.1)
            assert not blocked.done()  # paced while the buffer is high
            slow.buffered = 0          # receiver drained
            slow_r = await asyncio.wait_for(blocked, timeout=5)
            return fast_r, fast_dt, slow_r

        fast_r, fast_dt, slow_r = c.io.run(scenario(), timeout=60)
        assert fast_r == {"served": True} and slow_r == {"served": True}
        assert fast_dt < 0.05  # unblocked peer never waits
        # the pacing wait is transport-event-driven: water marks were
        # set once on the paced peer's connection, to the serve gate
        # (~2 chunks — responses stream from a small buffer; the window
        # stays the absolute flooded-peer cap)
        from ray_tpu.core import node_agent as na

        gate = min(window, 2 * na._chunk_size())
        assert slow.limits == (gate, gate // 2)
        assert fast.limits is None  # fast path never touches limits
    finally:
        c.shutdown()
