"""Pull prioritization + store-pressure admission (reference
src/ray/object_manager/pull_manager.h:52)."""

import asyncio
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core import pull_manager as pm
from ray_tpu.cluster_utils import Cluster


class FakeStore:
    def __init__(self, capacity=1000):
        self._cap = capacity
        self.used = 0

    def used_bytes(self):
        return self.used

    def capacity(self):
        return self._cap


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_priority_order_and_escalation():
    """Task-arg pulls activate before earlier-queued restores; a hot
    duplicate escalates a queued restore."""
    order = []
    gate = None  # first pull blocks until we release it

    async def main():
        nonlocal gate
        gate = asyncio.Event()

        async def pull(oid, deadline, reserve):
            order.append(oid)
            if oid == b"hold":
                await gate.wait()
            return True

        s = pm.PullScheduler(pull, FakeStore(), max_active=1)
        first = s.request(b"hold", pm.PRI_GET, 10)  # occupies the slot
        await asyncio.sleep(0.05)
        r_restore = s.request(b"restore", pm.PRI_RESTORE, 10)
        r_restore2 = s.request(b"restore2", pm.PRI_RESTORE, 10)
        r_arg = s.request(b"arg", pm.PRI_TASK_ARG, 10)  # queued LAST
        s.request(b"restore2", pm.PRI_TASK_ARG, 10)     # escalate
        await asyncio.sleep(0.05)
        gate.set()
        assert await asyncio.wait_for(r_arg, 5)
        assert await asyncio.wait_for(r_restore, 5)
        assert await asyncio.wait_for(r_restore2, 5)
        assert await asyncio.wait_for(first, 5)

    _run(main())
    assert order[0] == b"hold"
    # hottest first once the slot frees: arg and the escalated restore2
    # both run before the plain restore
    assert order.index(b"arg") < order.index(b"restore")
    assert order.index(b"restore2") < order.index(b"restore")


def test_admission_gates_on_headroom():
    """With the store above the watermark, only ONE pull is admitted at
    a time (forward progress), not the full max_active fan-out."""
    store = FakeStore(capacity=1000)
    store.used = 900  # above the 0.8 watermark
    concurrent = []
    peak = []

    async def main():
        async def pull(oid, deadline, reserve):
            reserve(100)
            concurrent.append(oid)
            peak.append(len([1 for _ in concurrent]))
            await asyncio.sleep(0.05)
            concurrent.remove(oid)
            return True

        s = pm.PullScheduler(pull, store, max_active=8)
        futs = [s.request(bytes([i]) * 4, pm.PRI_GET, 10)
                for i in range(6)]
        assert all(await asyncio.wait_for(asyncio.gather(*futs), 10))

    _run(main())
    assert max(peak) == 1  # serialized under pressure


def test_admission_fans_out_with_headroom():
    store = FakeStore(capacity=10_000)
    active = []
    peak = []

    async def main():
        async def pull(oid, deadline, reserve):
            reserve(10)
            active.append(oid)
            peak.append(len(active))
            await asyncio.sleep(0.05)
            active.remove(oid)
            return True

        s = pm.PullScheduler(pull, store, max_active=4)
        futs = [s.request(bytes([i]) * 4, pm.PRI_GET, 10)
                for i in range(8)]
        assert all(await asyncio.wait_for(asyncio.gather(*futs), 10))

    _run(main())
    assert max(peak) == 4  # capped by max_active, not serialized


def test_dedup_and_timeout():
    async def main():
        calls = []

        async def pull(oid, deadline, reserve):
            calls.append(oid)
            await asyncio.sleep(0.2)
            return True

        s = pm.PullScheduler(pull, FakeStore(), max_active=2)
        a = s.request(b"x", pm.PRI_GET, 10)
        b = s.request(b"x", pm.PRI_GET, 10)
        assert a is b  # shared future
        assert await asyncio.wait_for(a, 5)
        assert calls == [b"x"]
        # an expired queued request resolves False, doesn't hang
        blocker_gate = asyncio.Event()

        async def slow_pull(oid, deadline, reserve):
            await blocker_gate.wait()
            return True

        s2 = pm.PullScheduler(slow_pull, FakeStore(), max_active=1)
        s2.request(b"b1", pm.PRI_GET, 30)
        s2.request(b"b2", pm.PRI_GET, 30)  # fills queue behind b1
        doomed = s2.request(b"late", pm.PRI_RESTORE, 0.1)
        assert (await asyncio.wait_for(doomed, 5)) is False
        blocker_gate.set()

    _run(main())


def test_pulls_exceeding_capacity_make_progress():
    """E2E chaos-under-pressure: a node pulls a working set LARGER than
    its store; admission + LRU eviction keep every task completing
    instead of OOM-killing the store."""
    cap = 32 * 1024 * 1024
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30},
                store_capacity=cap)
    c.connect()
    second = c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    try:
        # 12 x 4MB objects created on the head node = 48MB > 32MB store
        blobs = [ray_tpu.put(np.full(1024 * 1024, i, np.float32))
                 for i in range(12)]

        @ray_tpu.remote(num_cpus=2)
        def consume(x, i):
            return float(x[0]) == float(i) and x.nbytes == 4 * 1024 * 1024

        # num_cpus=2 forces spillback spread; every dep must be pulled
        # to whichever node runs the task
        out = ray_tpu.get(
            [consume.remote(b, i) for i, b in enumerate(blobs)],
            timeout=300,
        )
        assert all(out), out
        assert second.store.used_bytes() <= cap
    finally:
        c.shutdown()
