"""Tune schedulers: MedianStopping, HyperBand brackets, PBT.

Reference test models: tune/tests/test_trial_scheduler.py,
test_trial_scheduler_pbt.py — unit-level decision checks plus an
end-to-end PBT run on the cluster fixture where exploitation provably
transfers good hyperparams via checkpoints.
"""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


# ---------------- unit: decision logic ----------------

def test_median_stopping_rule():
    s = tune.MedianStoppingRule(mode="min", grace_period=2,
                                min_samples_required=2)
    # three trials report; t_bad consistently worse
    for it in (1, 2, 3):
        assert s.on_result("a", it, 1.0) == "continue"
        assert s.on_result("b", it, 1.1) == "continue"
        if it < 3:
            s.on_result("bad", it, 9.0)
    assert s.on_result("bad", 3, 9.0) == "stop"


def test_median_stopping_respects_grace():
    s = tune.MedianStoppingRule(mode="min", grace_period=5,
                                min_samples_required=2)
    s.on_result("a", 1, 1.0)
    s.on_result("b", 1, 1.0)
    assert s.on_result("bad", 1, 99.0) == "continue"  # still in grace


def test_hyperband_brackets_differ_in_grace():
    s = tune.HyperBandScheduler(mode="min", max_t=27, reduction_factor=3,
                                num_brackets=2)
    s.metric = "loss"
    # trial A -> bracket 0 (grace 1), trial B -> bracket 1 (grace 3):
    # at iteration 1, bracket 0 has a rung, bracket 1 does not
    assert s.on_result("A", 1, 5.0) == "continue"  # first at rung: optimism
    assert s.on_result("B", 1, 500.0) == "continue"  # no rung at 1 in b1
    # fill bracket-0 rung 1 with better peers -> a bad new arrival stops
    for i, v in enumerate((1.0, 1.1, 1.2, 1.3)):
        s._assignment[f"peer{i}"] = 0
        s.on_result(f"peer{i}", 1, v)
    s._assignment["loser"] = 0
    assert s.on_result("loser", 1, 400.0) == "stop"
    # bracket 1 never cuts at iteration 1 no matter how bad
    s._assignment["b1-loser"] = 1
    assert s.on_result("b1-loser", 1, 1e9) == "continue"


def test_pbt_exploit_decision_and_explore():
    s = tune.PopulationBasedTraining(
        mode="min", perturbation_interval=2,
        hyperparam_mutations={"lr": tune.loguniform(1e-4, 1e-1)},
        quantile_fraction=0.25, seed=0,
    )
    # 8 trials: t0 best ... t7 worst; decisions at iteration 2
    for i in range(8):
        s.on_result(f"t_{i:04d}", 1, float(i))
    decisions = {
        i: s.on_result(f"t_{i:04d}", 2, float(i)) for i in range(8)
    }
    assert decisions[0] == "continue"  # top stays
    bottom = [d for i, d in decisions.items() if i >= 6]
    assert any(isinstance(d, tuple) and d[0] == "exploit" for d in bottom)
    for d in decisions.values():
        if isinstance(d, tuple):
            donor_rank = int(d[1].rsplit("_", 1)[1])
            assert donor_rank <= 1  # donors come from the top quantile
    # explore mutates lr but keeps other keys
    cfg = s.explore({"lr": 0.01, "batch": 32})
    assert cfg["batch"] == 32
    assert cfg["lr"] != 0.01 or True  # either jittered or resampled
    assert 1e-5 < cfg["lr"] < 1.0


# ---------------- end-to-end PBT ----------------

@pytest.mark.slow  # ~7s e2e; PBT exploit/explore decision units above are tier-1
def test_pbt_end_to_end_transfers_good_config(cluster):
    """Trainables descend toward loss=|lr-0.1|; bad-lr trials must adopt
    (a mutation of) the good trial's lr via exploit+checkpoint."""

    def trainable(config):
        lr = config["lr"]
        ckpt = tune.get_checkpoint()
        step = ckpt["step"] if ckpt else 0
        for it in range(12):
            step += 1
            # lr dominates; the step term is small so inter-trial report
            # staleness can't mask the hyperparam signal
            loss = abs(lr - 0.1) + 0.01 / (1 + step)
            tune.report({"loss": loss}, checkpoint={"step": step, "lr": lr})

    sched = tune.PopulationBasedTraining(
        mode="min", perturbation_interval=3,
        hyperparam_mutations={"lr": [0.001, 0.01, 0.1]},
        quantile_fraction=0.25, seed=1,
    )
    results = tune.Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.001, 0.002, 0.1, 0.005])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", scheduler=sched,
            max_concurrent_trials=4,
        ),
    ).fit()
    assert sched.num_perturbations >= 1, "PBT never exploited"
    best = results.get_best_result()
    assert best.metrics["loss"] < 0.01
    # at least one trial ended on a config it did not start with (the
    # exploit+explore path rewrote it from a donor)
    final_lrs = sorted(r.config["lr"] for r in results)
    assert final_lrs != [0.001, 0.002, 0.005, 0.1]
