"""In-place elastic resume for JaxTrainer (dcn backend).

The acceptance bar: an injected single-rank death mid-training resumes
IN-PLACE — survivor PIDs unchanged, no `BackendExecutor.start()`
re-entry, dataset shards rebalanced without restarting survivors'
iterators from epoch 0 — with post-resume loss/parameter parity against
an uninterrupted run, and `train_resume_total` proving the common path
stays `mode="inplace"`. Plus driver-side units: `_drain`'s per-rank
report buffering and unequal-results error path, typed dead-rank
classification, the shutdown-must-not-mask-the-error guard, DataShard
cursor semantics, and checkpoint torn-write/bitrot fallback.
"""

import json
import os
import sys

import cloudpickle
import numpy as np
import pytest

from ray_tpu._private import fault_injection as fi
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import (
    Checkpoint,
    CheckpointCorruptError,
    CheckpointManager,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    verify_checkpoint,
)
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError
from ray_tpu.train.session import DataShard

# worker subprocesses can't import the tests package: ship helpers by value
cloudpickle.register_pickle_by_value(sys.modules[__name__])

N_BLOCKS = 8
DIM = 16
LR = 0.1
STEPS = 6
WORLD = 3


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


# ---------------------------------------------------------------------------
# the train loop (shipped by value): world-size-invariant summed gradients
# ---------------------------------------------------------------------------


def _block_grad(i, step):
    rng = np.random.default_rng(7919 * (i + 1) + step)
    return rng.standard_normal(DIM).astype(np.float32)


def _ref_params(steps):
    """Closed-form fault-free schedule: grads are summed over ALL blocks
    each step, so any partitioning of blocks over any world size yields
    the same update (modulo f32 summation order)."""
    p = np.zeros(DIM, np.float32)
    for s in range(steps):
        total = np.zeros(DIM, np.float32)
        for i in range(N_BLOCKS):
            total = total + _block_grad(i, s)
        p = p - LR * (total / N_BLOCKS)
    return p


def _elastic_loop(config):
    """Runs identically on every worker; each step sums its shard's block
    gradients and DCN-allreduces the total. Chaos specs arm on the FIRST
    incarnation only (`resume_seq == 0`), so resumed/respawned processes
    never re-trip exhausted faults."""
    import json as _json
    import os as _os

    import numpy as _np

    from ray_tpu._private import fault_injection as _fi
    from ray_tpu.train import dcn_allreduce_grads, session
    from ray_tpu.train.checkpoint import Checkpoint as _Ck

    rank = session.get_world_rank()
    seq = session.get_resume_seq()
    specs = config.get("worker_specs") or []
    if seq == 0 and specs:
        kill_rank = config.get("kill_rank")
        if kill_rank is None or rank == kill_rank:
            _fi.configure(specs)
    shard = session.get_dataset_shard("train")
    group = session.get_collective_group()
    with open(_os.path.join(
            config["out"],
            f"inc_r{rank}_s{seq}_{_os.getpid()}.json"), "w") as f:
        _json.dump({"pid": _os.getpid(), "rank": rank, "resume_seq": seq,
                    "world": session.get_world_size(),
                    "indices": shard.assigned_indices(),
                    "shard_epoch": shard.epoch}, f)
    params = _np.zeros(DIM, _np.float32)
    start = 0
    ck = session.get_checkpoint()
    if ck is not None:
        d = ck.to_dict()
        params = _np.asarray(d["params"], _np.float32)
        start = int(d["step"])
    for step in range(start, config["steps"]):
        for _block in shard:  # one epoch pass: advances the cursor
            pass
        contrib = _np.zeros(DIM, _np.float32)
        for i in shard.assigned_indices():
            contrib = contrib + _block_grad(i, step)
        total = dcn_allreduce_grads({"g": contrib}, group, op="sum",
                                    timeout=30.0)["g"]
        params = params - LR * (total / N_BLOCKS)
        ckpt = None
        if rank == 0:
            ckpt = _Ck.from_dict(
                {"step": step + 1, "params": params},
                _os.path.join(config["ck_dir"], f"ck_s{seq}_{step}"))
        session.report({"step": step + 1,
                        "loss": float(_np.square(params).sum())},
                       checkpoint=ckpt)


def _stubborn_loop(config):
    """Swallows the collective abort and keeps 'training' — the wedged
    survivor the quiesce must detect, forcing the gang fallback."""
    import time as _time

    import numpy as _np

    from ray_tpu._private import fault_injection as _fi
    from ray_tpu.collective import CollectiveAbortError
    from ray_tpu.train import dcn_allreduce_grads, session

    rank = session.get_world_rank()
    seq = session.get_resume_seq()
    if seq == 0 and rank == config.get("kill_rank"):
        _fi.configure(config["worker_specs"])
    group = session.get_collective_group()
    for step in range(config["steps"]):
        try:
            dcn_allreduce_grads(
                {"g": _np.ones(4, _np.float32) * rank}, group, op="sum",
                timeout=30.0)
        except CollectiveAbortError:
            if seq == 0:
                _time.sleep(120)  # wedged in "user code"
            raise
        session.report({"step": step + 1})


def _scaling(world=WORLD, min_workers=1):
    return ScalingConfig(
        num_workers=world,
        resources_per_worker={"CPU": 1},
        backend="dcn",
        min_workers=min_workers,
        placement_strategy="PACK",
    )


def _resume_metric_values():
    from ray_tpu.util import metrics as M

    for m in list(M._registry):
        if m.name == "train_resume_total":
            with m._lock:
                return {dict(k).get("mode"): v
                        for k, v in m._values.items()}
    return {}


def _read_incarnations(out):
    incs = {}
    for fn in os.listdir(out):
        if fn.startswith("inc_"):
            with open(os.path.join(out, fn)) as f:
                d = json.load(f)
            incs.setdefault(d["resume_seq"], {})[d["rank"]] = d
    return incs


# ---------------------------------------------------------------------------
# acceptance: single-rank death resumes in-place
# ---------------------------------------------------------------------------


def test_single_rank_death_resumes_inplace(cluster, tmp_path, monkeypatch):
    out = tmp_path / "inc"
    out.mkdir()
    starts = []
    orig_start = BackendExecutor.start

    def counting_start(self):
        starts.append(1)
        return orig_start(self)

    monkeypatch.setattr(BackendExecutor, "start", counting_start)
    before = _resume_metric_values()

    # rank 1 hard-exits at its 6th ring chunk send (mid step 1); only the
    # victim arms the spec, so survivors can't re-trip it post-compaction
    trainer = JaxTrainer(
        _elastic_loop,
        train_loop_config={
            "steps": STEPS, "out": str(out), "ck_dir": str(tmp_path / "ck"),
            "worker_specs": [{"site": "ring.send", "match": {"rank": 1},
                              "after": 5, "action": "exit", "count": 1}],
            "kill_rank": 1,
        },
        scaling_config=_scaling(),
        run_config=RunConfig(name="inplace", storage_path=str(tmp_path),
                             max_failures=1, max_inplace_resumes=4),
        datasets={"train": list(range(N_BLOCKS))},
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["step"] == STEPS

    # the failure was absorbed IN-PLACE: one inplace resume, zero gang
    # restarts, and the executor cold-started exactly once
    assert result.resumes == {"inplace": 1, "gang": 0}
    assert len(starts) == 1, "BackendExecutor.start() was re-entered"
    after = _resume_metric_values()
    assert after.get("inplace", 0) - before.get("inplace", 0) == 1
    assert after.get("gang", 0) == before.get("gang", 0)

    incs = _read_incarnations(out)
    assert set(incs) == {0, 1}
    assert set(incs[0]) == {0, 1, 2}
    # capacity was still there, so the gang re-grew to the target world
    assert set(incs[1]) == {0, 1, 2}
    victim_pid = incs[0][1]["pid"]
    pids0 = {d["pid"] for d in incs[0].values()}
    pids1 = {d["pid"] for d in incs[1].values()}
    # survivors kept their PROCESSES; the victim's pid is gone
    assert (pids0 - {victim_pid}) <= pids1
    assert victim_pid not in pids1

    # dataset shards rebalanced: disjoint cover of all blocks at seq 1
    all_idx = []
    for d in incs[1].values():
        all_idx.extend(d["indices"])
    assert sorted(all_idx) == list(range(N_BLOCKS))
    # survivors' iterators kept their epoch cursor (not reset to 0);
    # only the freshly spawned replacement starts at epoch 0
    surv_epochs = [d["shard_epoch"] for d in incs[1].values()
                   if d["pid"] in pids0]
    fresh_epochs = [d["shard_epoch"] for d in incs[1].values()
                    if d["pid"] not in pids0]
    assert surv_epochs and all(e >= 1 for e in surv_epochs), surv_epochs
    assert all(e == 0 for e in fresh_epochs), fresh_epochs

    # post-resume parity with an uninterrupted run (f32 ring order-tol)
    final = result.checkpoint.to_dict()
    assert final["step"] == STEPS
    np.testing.assert_allclose(
        np.asarray(final["params"]), _ref_params(STEPS),
        rtol=1e-5, atol=1e-6)
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] == pytest.approx(
        float(np.square(_ref_params(STEPS)).sum()), rel=1e-4)


@pytest.mark.slow  # ~22s (20s wedge quiesce timeout by design); the
# in-place and gang-restart paths keep tier-1 coverage via the
# single-rank-death and checkpoint-resume tests in this file
def test_wedged_survivor_falls_back_to_gang_restart(cluster, tmp_path):
    """If a survivor won't quiesce (user code swallows the abort), the
    in-place path must give up and the gang restart must still converge."""
    from ray_tpu._private import config as _cfg

    _cfg.set_system_config({"train_quiesce_timeout_s": 4.0})
    try:
        trainer = JaxTrainer(
            _stubborn_loop,
            train_loop_config={
                "steps": 3,
                "worker_specs": [{"site": "ring.send", "match": {"rank": 1},
                                  "after": 2, "action": "exit", "count": 1}],
                "kill_rank": 1,
            },
            scaling_config=_scaling(world=2),
            run_config=RunConfig(name="wedge", storage_path=str(tmp_path),
                                 max_failures=1, max_inplace_resumes=4),
        )
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics["step"] == 3
        assert result.resumes == {"inplace": 0, "gang": 1}
    finally:
        _cfg.set_system_config({"train_quiesce_timeout_s": 30.0})


# ---------------------------------------------------------------------------
# _drain units: buffering, unequal results, typed dead-rank classification
# ---------------------------------------------------------------------------


class _FakeExec:
    def __init__(self, rounds):
        self.num_workers = len(rounds[0])
        self._it = iter(rounds)

    def next_results(self, timeout=10.0):
        return next(self._it)


def _rep(step, rank):
    return {"type": "report", "metrics": {"step": step, "src_rank": rank}}


def _drain_with(rounds, tmp_path):
    trainer = JaxTrainer(lambda c: None)
    mgr = CheckpointManager(str(tmp_path / "drainmgr"))
    history = []
    final = trainer._drain(_FakeExec(rounds), mgr, history)
    return final, history


def test_drain_buffers_reports_per_rank(tmp_path):
    """One rank running a full step ahead must not duplicate or reorder
    history: a step is recorded once BOTH ranks reported it, with rank
    0's metrics authoritative."""
    rounds = [
        [_rep(1, 0), {"type": "pending"}],          # rank 0 a step ahead
        [_rep(2, 0), _rep(1, 1)],                   # step 1 completes
        [{"type": "finished"}, _rep(2, 1)],         # step 2 completes
        [{"type": "finished"}, {"type": "finished"}],
    ]
    final, history = _drain_with(rounds, tmp_path)
    assert [m["step"] for m in history] == [1, 2]
    assert all(m["src_rank"] == 0 for m in history)
    assert final == {"step": 2, "src_rank": 0}


def test_drain_unequal_results_is_error(tmp_path):
    """All ranks finished but one left an undrained report: lockstep was
    violated — typed failure, not silent truncation."""
    rounds = [
        [_rep(1, 0), {"type": "finished"}],
        [{"type": "finished"}, {"type": "finished"}],
    ]
    with pytest.raises(TrainingFailedError, match="unequal numbers"):
        _drain_with(rounds, tmp_path)


def test_drain_prefers_typed_abort_over_generic_death(tmp_path):
    """A dead rank plus a survivor's CollectiveAbortError must classify
    as the abort (it drives the in-place decision) AND name the dead
    ranks."""
    rounds = [[
        {"type": "error", "error": "tb...", "error_type":
         "CollectiveAbortError"},
        {"type": "dead", "error": "RayActorError: actor died"},
    ]]
    with pytest.raises(TrainingFailedError) as ei:
        _drain_with(rounds, tmp_path)
    assert ei.value.error_type == "CollectiveAbortError"
    assert ei.value.dead_ranks == [1]


def test_drain_death_alone_synthesizes_worker_died(tmp_path):
    rounds = [[{"type": "pending"},
               {"type": "dead", "error": "RayActorError: gone"}]]
    with pytest.raises(TrainingFailedError) as ei:
        _drain_with(rounds, tmp_path)
    assert ei.value.error_type == "WorkerDiedError"
    assert ei.value.dead_ranks == [1]


def test_shutdown_quietly_never_masks_the_failure():
    class _Boom:
        def shutdown(self):
            raise RuntimeError("agent connection lost during teardown")

    JaxTrainer._shutdown_quietly(_Boom())  # must not raise
    JaxTrainer._shutdown_quietly(None)


# ---------------------------------------------------------------------------
# DataShard cursor semantics (elastic rebalance without epoch reset)
# ---------------------------------------------------------------------------


def test_datashard_epoch_and_cursor():
    sh = DataShard("t", [f"b{i}" for i in range(6)], [0, 2, 4])
    assert [b for b in sh] == ["b0", "b2", "b4"]
    assert sh.epoch == 1 and sh.state()["consumed"] == []
    it = iter(sh)
    assert next(it) == "b0"
    assert sh.state() == {"epoch": 1, "consumed": [0]}


def test_datashard_reassign_preserves_survivor_cursor():
    sh = DataShard("t", list(range(8)), [0, 1, 2])
    it = iter(sh)
    next(it), next(it)  # consumed {0, 1}, mid-epoch
    sh.reassign([0, 1, 2, 5, 7])  # adopt a dead rank's blocks
    assert sh.state() == {"epoch": 0, "consumed": [0, 1]}
    # the rest of THIS epoch: retained unconsumed + adopted blocks
    assert [b for b in sh] == [2, 5, 7]
    assert sh.epoch == 1
    # losing blocks drops their cursor state too
    it = iter(sh)
    next(it)
    sh.reassign([1, 2])
    assert sh.state()["consumed"] == []  # consumed block 0 was lost
    assert sorted(sh.assigned_indices()) == [1, 2]


def test_datashard_cursor_checkpoint_roundtrip():
    """state()/load_state(): checkpointing the cursor next to the model
    state lets a rollback rewind the data cursor too, so blocks consumed
    after the checkpoint are re-delivered instead of skipped."""
    sh = DataShard("t", list(range(6)), [0, 1, 2, 3])
    it = iter(sh)
    next(it)  # consumed {0} — checkpoint here
    snap = sh.state()
    next(it), next(it)  # consumed {0,1,2} after the checkpoint
    sh.load_state(snap)  # rollback to the checkpoint
    assert [b for b in sh] == [1, 2, 3]  # 1 and 2 re-delivered
    # restore composes with a rebalanced assignment: foreign indices drop
    sh.load_state({"epoch": 3, "consumed": [0, 5]})
    assert sh.state() == {"epoch": 3, "consumed": [0]}


def test_datashard_break_does_not_bump_epoch():
    sh = DataShard("t", list(range(4)), [0, 1, 2, 3])
    for b in sh:
        if b == 1:
            break
    assert sh.epoch == 0 and sh.state()["consumed"] == [0, 1]
    assert [b for b in sh] == [2, 3]
    assert sh.epoch == 1


# ---------------------------------------------------------------------------
# checkpoint integrity: torn writes, bitrot, fallback chain
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()


def test_torn_checkpoint_write_is_typed_and_falls_back(tmp_path):
    good = Checkpoint.from_dict({"step": 1}, str(tmp_path / "good"))
    fi.configure([{"site": "checkpoint.save", "action": "drop"}])
    torn = Checkpoint.from_dict({"step": 2}, str(tmp_path / "torn"))
    fi.clear()
    with pytest.raises(CheckpointCorruptError, match="crc32"):
        torn.to_dict()
    mgr = CheckpointManager(str(tmp_path / "mgr"))
    mgr.register(good)
    mgr.register(torn)
    assert mgr.latest.path == torn.path
    lv = mgr.latest_valid()
    assert lv is not None and lv.path == good.path
    assert mgr.latest.path == good.path  # corrupt one was discarded
    assert lv.to_dict() == {"step": 1}


def test_injected_bitrot_on_restore_is_typed(tmp_path):
    ck = Checkpoint.from_dict({"step": 3}, str(tmp_path / "ck"))
    fi.configure([{"site": "checkpoint.restore", "action": "drop"}])
    with pytest.raises(CheckpointCorruptError, match="bitrot"):
        ck.to_dict()
    # the injection was count=1: the checkpoint itself is intact
    assert ck.to_dict() == {"step": 3}


def test_sharded_save_is_checksummed(tmp_path):
    """save_state/restore_state ride the same integrity rail as dict
    checkpoints: flipping bytes in a shard file is caught typed."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.train import restore_state, save_state

    path = str(tmp_path / "sck")
    save_state({"w": jnp.arange(8.0), "step": 1}, path,
               extra={"tag": "x"})
    verify_checkpoint(path)
    got = restore_state(path, mesh=None, shardings={
        "w": jax.sharding.SingleDeviceSharding(jax.devices()[0]),
        "step": None})
    np.testing.assert_allclose(np.asarray(got["w"]), np.arange(8.0))
    shard_file = os.path.join(path, "shards_p0.npz")
    with open(shard_file, "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff")
    # shard archives verify lazily on first read: the corrupt file is
    # caught the moment a piece is loaded from it
    with pytest.raises(CheckpointCorruptError):
        restore_state(path, mesh=None, shardings={
            "w": jax.sharding.SingleDeviceSharding(jax.devices()[0]),
            "step": None})


def test_truncated_shard_archive_is_typed(tmp_path):
    """A write torn at the zip central directory fails at archive OPEN
    (before any member crc check can run) — still the typed error, not
    a BadZipFile traceback the trainer would classify as a user bug."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.train import restore_state, save_state

    path = str(tmp_path / "tck")
    save_state({"w": jnp.arange(8.0)}, path)
    shard_file = os.path.join(path, "shards_p0.npz")
    with open(shard_file, "r+b") as f:
        f.truncate(os.path.getsize(shard_file) - 30)
    with pytest.raises(CheckpointCorruptError, match="unreadable"):
        restore_state(path, mesh=None, shardings={
            "w": jax.sharding.SingleDeviceSharding(jax.devices()[0])})


class _FakeHandle:
    """Stand-in actor handle: _rebalance_assignments only reads _actor_id."""

    def __init__(self, aid):
        self._actor_id = aid


def test_rebalance_levels_regrown_worker():
    """After shrink-then-grow every block is already assigned (no
    orphans), so levelling must move excess off survivors or the fresh
    worker idles with an empty shard for the rest of the run."""
    ex = BackendExecutor(3, backend="dcn",
                         datasets={"train": list(range(4))})
    a, b, c = _FakeHandle(b"a"), _FakeHandle(b"b"), _FakeHandle(b"c")
    wg = type("_WG", (), {})()
    wg.workers = [a, b, c]  # c just re-grown, holds nothing
    ex.worker_group = wg
    ex._assignments = {"train": {b"a": [0, 1], b"b": [2, 3]}}
    ex._rebalance_assignments()
    per = ex._assignments["train"]
    assert sorted(i for v in per.values() for i in v) == [0, 1, 2, 3]
    assert sorted(len(v) for v in per.values()) == [1, 1, 2]
    assert len(per[b"c"]) == 1  # the regrown worker got real work
    # survivors keep their longest-held blocks (pop moves the tail)
    assert per[b"a"][0] == 0 and per[b"b"][0] == 2


def test_warm_resume_without_checkpoint_resets_cursors():
    """A warm resume with no checkpoint restarts the MODEL from scratch,
    so surviving cursors must restart too — otherwise the fresh model
    trains on a strict subset of the epoch (blocks consumed by training
    that was lost with the old parameters)."""
    from ray_tpu._private import serialization
    from ray_tpu.train.backend_executor import _start_training

    w = type("_W", (), {})()
    w.worker_idx = 0
    w.state = {}
    sh = DataShard("train", list(range(4)), [0, 1])
    next(iter(sh))  # consume one block, then "fail"
    assert sh._consumed
    w.state["dataset_shards"] = {"train": sh}
    blob = serialization.pack_callable(lambda cfg: None)
    _start_training(w, blob, {}, None, rank=0, world_size=1,
                    shard_plan={"train": (None, [0, 1])}, resume_seq=1)
    w.state["train_thread"].join(5)
    assert sh.epoch == 0 and not sh._consumed
    # with a checkpoint the cursor is preserved (anchored to the
    # restored model state)
    sh2 = DataShard("train", list(range(4)), [0, 1])
    next(iter(sh2))
    w.state["dataset_shards"] = {"train": sh2}
    _start_training(w, blob, {}, "/nonexistent-but-unused", rank=0,
                    world_size=1,
                    shard_plan={"train": (None, [0, 1])}, resume_seq=1)
    w.state["train_thread"].join(5)
    assert sh2._consumed == {0}


@pytest.mark.slow
def test_runtime_restarted_rank_resumes_inplace(cluster, tmp_path):
    """max_restarts > 0: the control plane restarts a hard-exited rank
    under the SAME actor id with a fresh, state-empty process. The heal
    must detect the reborn member (actor-id bookkeeping alone calls it
    an intact survivor), re-run backend setup, and re-ship its blocks —
    otherwise every in-place resume wedges on 'no blocks shipped'."""
    from ray_tpu._private import config as _cfg

    out = tmp_path / "inc"
    out.mkdir()
    scaling = _scaling(world=2)
    scaling.max_restarts = 1
    # the quiesce bound also sizes heal()'s wait-for-runtime-restart
    # window; the default 30s makes this test crawl while the restart
    # itself lands in a couple of seconds
    _cfg.set_system_config({"train_quiesce_timeout_s": 8.0})
    trainer = JaxTrainer(
        _elastic_loop,
        train_loop_config={
            "steps": STEPS, "out": str(out), "ck_dir": str(tmp_path / "ck"),
            "worker_specs": [{"site": "ring.send", "match": {"rank": 1},
                              "after": 4, "action": "exit", "count": 1}],
            "kill_rank": 1,
        },
        scaling_config=scaling,
        run_config=RunConfig(name="reborn", storage_path=str(tmp_path),
                             max_failures=1, max_inplace_resumes=4),
        datasets={"train": list(range(N_BLOCKS))},
    )
    try:
        result = trainer.fit()
    finally:
        _cfg.set_system_config({"train_quiesce_timeout_s": 30.0})
    assert result.error is None, result.error
    assert result.metrics["step"] == STEPS
    assert result.resumes == {"inplace": 1, "gang": 0}, result.resumes
    incs = _read_incarnations(out)
    assert set(incs) == {0, 1}
    assert set(incs[1]) == {0, 1}  # back at the target world
    # every block covered after the resume (the reborn member was
    # re-shipped its block list, not handed blocks=None)
    all_idx = []
    for d in incs[1].values():
        all_idx.extend(d["indices"])
    assert sorted(all_idx) == list(range(N_BLOCKS))
    # the surviving rank kept its process
    assert incs[0][0]["pid"] in {d["pid"] for d in incs[1].values()}
    np.testing.assert_allclose(
        np.asarray(result.checkpoint.to_dict()["params"]),
        _ref_params(STEPS), rtol=1e-5, atol=1e-6)


def test_missing_writer_record_is_typed(tmp_path):
    """Losing an entire writer's pair (shards + checksum record) must
    fail verification via the meta writer manifest — the merged records
    would otherwise pass vacuously and restore silent zeros."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.train import restore_state, save_state

    path = str(tmp_path / "wck")
    save_state({"w": jnp.arange(8.0)}, path)
    os.remove(os.path.join(path, "shards_p0.npz"))
    os.remove(os.path.join(path, "checksums_p0.json"))
    with pytest.raises(CheckpointCorruptError, match="writer record"):
        verify_checkpoint(path)
    with pytest.raises(CheckpointCorruptError, match="writer record"):
        restore_state(path, mesh=None, shardings={
            "w": jax.sharding.SingleDeviceSharding(jax.devices()[0])})


def test_rebalance_orphans_prefer_fresh_member():
    """A same-size replacement (respawn/grow) re-adopts its dead
    predecessor's blocks on load ties, so survivors don't pick up
    extra at-least-once re-reads."""
    ex = BackendExecutor(3, backend="dcn",
                         datasets={"train": list(range(7))})
    a, b, c = _FakeHandle(b"a"), _FakeHandle(b"b"), _FakeHandle(b"c")
    wg = type("_WG", (), {})()
    wg.workers = [a, b, c]  # c replaced a dead rank that held 3 blocks
    ex.worker_group = wg
    ex._assignments = {"train": {b"a": [0, 1], b"b": [2, 3],
                                 b"dead": [4, 5, 6]}}
    ex._rebalance_assignments()
    per = ex._assignments["train"]
    assert sorted(i for v in per.values() for i in v) == list(range(7))
    # survivors untouched; the fresh member took ALL the orphans
    assert per[b"a"] == [0, 1] and per[b"b"] == [2, 3]
    assert sorted(per[b"c"]) == [4, 5, 6]
