"""fused_adamw: trajectory parity vs optax.adamw + low-precision moments.

The fused optimizer exists for HBM efficiency (one pass per leaf vs
optax's chain — see train/optim.py); these tests pin its MATH to optax's.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.train.optim import fused_adamw


def _params():
    k = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(k)
    return {
        "w": jax.random.normal(k1, (16, 8)) * 0.1,
        "inner": {"b": jax.random.normal(k2, (8,)) * 0.1},
    }


def _run(opt, params, grads, n=10):
    state = opt.init(params)
    upd = jax.jit(opt.update)
    for _ in range(n):
        updates, state = upd(grads, state, params)
        params = optax.apply_updates(params, updates)
    return params


def test_matches_optax_adamw_f32():
    params = _params()
    grads = jax.tree_util.tree_map(lambda x: 0.05 * jnp.sin(x * 7), params)
    p1 = _run(optax.adamw(3e-3, weight_decay=0.01), params, grads)
    p2 = _run(fused_adamw(3e-3, weight_decay=0.01), params, grads)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mu_dtype,nu_dtype", [
    (jnp.bfloat16, None), (jnp.bfloat16, jnp.bfloat16)])
def test_low_precision_moments_stay_close(mu_dtype, nu_dtype):
    params = _params()
    grads = jax.tree_util.tree_map(lambda x: 0.05 * jnp.cos(x * 3), params)
    exact = _run(fused_adamw(3e-3, weight_decay=0.01), params, grads)
    lowp = _run(fused_adamw(3e-3, weight_decay=0.01, mu_dtype=mu_dtype,
                            nu_dtype=nu_dtype), params, grads)
    for a, b in zip(jax.tree_util.tree_leaves(exact),
                    jax.tree_util.tree_leaves(lowp)):
        # moments in bf16 perturb the update by O(2^-8) relative
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0.02, atol=1e-3)


def test_schedule_and_weight_decay():
    params = _params()
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    sched = optax.linear_schedule(1e-2, 1e-3, 10)
    p1 = _run(optax.adamw(sched, weight_decay=0.1), params, grads)
    p2 = _run(fused_adamw(sched, weight_decay=0.1), params, grads)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_stochastic_round_unbiased():
    from ray_tpu.train.optim import _stochastic_round_bf16

    x = jnp.full((100_000,), 1.001953125e-3, jnp.float32)  # between ulps
    means = []
    for i in range(10):
        key = jnp.uint32(i * 0x9E3779B9 % 2**32)
        means.append(float(jnp.mean(
            _stochastic_round_bf16(x, key).astype(jnp.float32))))
    np.testing.assert_allclose(np.mean(means), float(x[0]), rtol=1e-4)


def test_bf16_nu_ema_not_frozen():
    """With b2=0.999 the per-step nu change is below bf16 ulp; a truncating
    cast would freeze the EMA forever. Stochastic rounding must let it
    decay at the true 0.999^n rate."""
    opt = fused_adamw(1e-3, b2=0.999, nu_dtype=jnp.bfloat16)
    params = {"w": jnp.ones((512,))}
    state = opt.init(params)
    upd = jax.jit(opt.update)
    for _ in range(200):
        _, state = upd({"w": jnp.full((512,), 1.0)}, state, params)
    nu_big = float(jnp.mean(state.nu["w"].astype(jnp.float32)))
    for _ in range(1000):
        _, state = upd({"w": jnp.full((512,), 1e-3)}, state, params)
    nu_small = float(jnp.mean(state.nu["w"].astype(jnp.float32)))
    expected = nu_big * 0.999 ** 1000  # ~0.37x
    assert nu_small < nu_big * 0.6, "nu EMA is stuck"
    np.testing.assert_allclose(nu_small, expected, rtol=0.15)
