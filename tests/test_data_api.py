"""Dataset API breadth: column ops, unique/sample/std, tensor
extension columns (reference python/ray/data/dataset.py surface +
air/util/tensor_extensions/arrow.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data
from ray_tpu.cluster_utils import Cluster
from ray_tpu.data import tensor_ext


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _rows():
    return [{"a": i, "b": i * 2, "c": f"s{i}"} for i in range(20)]


def test_select_drop_rename(cluster):
    ds = data.from_items(_rows(), parallelism=4)
    sel = ds.select_columns(["a", "c"]).take_all()
    assert all(set(r) == {"a", "c"} for r in sel) and len(sel) == 20
    drp = ds.drop_columns(["b"]).take_all()
    assert all(set(r) == {"a", "c"} for r in drp)
    ren = ds.rename_columns({"a": "alpha"}).take_all()
    assert all("alpha" in r and "a" not in r for r in ren)
    assert set(ds.columns()) == {"a", "b", "c"}


def test_column_ops_on_arrow_blocks(cluster):
    import pyarrow as pa

    table = pa.Table.from_pylist(_rows())
    ds = data.from_arrow(table, parallelism=3)
    out = ds.select_columns(["b"]).take_all()
    assert [r["b"] for r in out] == [i * 2 for i in range(20)]
    ren = ds.rename_columns({"b": "bee"}).drop_columns(["c"]).take_all()
    assert set(ren[0]) == {"a", "bee"}


def test_unique_sample_std(cluster):
    ds = data.from_items([{"k": i % 4, "v": float(i)}
                          for i in range(40)], parallelism=4)
    assert ds.unique("k") == [0, 1, 2, 3]
    vals = [float(i) for i in range(40)]
    assert ds.std("v") == pytest.approx(np.std(vals, ddof=1))
    assert ds.var("v") == pytest.approx(np.var(vals, ddof=1))
    sampled = ds.random_sample(0.5, seed=7).take_all()
    assert 5 <= len(sampled) <= 35  # loose binomial bounds
    empty = data.from_items([{"v": 1.0}]).std("v")
    assert np.isnan(empty)


def test_take_all_limit_and_to_numpy(cluster):
    ds = data.range_(100, parallelism=4)
    with pytest.raises(ValueError, match="limit"):
        ds.take_all(limit=10)
    arr = data.from_numpy(np.arange(32).reshape(8, 4)).to_numpy()
    assert arr.shape == (8, 4)
    col = data.from_items(_rows()).to_numpy(column="a")
    assert col.tolist() == list(range(20))


def test_tensor_extension_roundtrip(cluster):
    imgs = np.arange(2 * 5 * 4 * 3, dtype=np.float32).reshape(10, 4, 3)
    table = tensor_ext.tensor_table(
        {"img": imgs, "label": list(range(10))})
    assert "tensor(4, 3)" in str(table.schema.field("img").type)
    ds = data.from_arrow(table, parallelism=3)
    # schema surfaces the tensor type; rows carry real ndarrays
    rows = ds.take_all()
    assert rows[3]["img"].shape == (4, 3)
    np.testing.assert_array_equal(rows[3]["img"], imgs[3])
    # row-wise map over tensor columns keeps the extension type
    doubled = ds.map(lambda r: {"img": r["img"] * 2,
                                "label": r["label"]})
    out = doubled.take_all()
    np.testing.assert_array_equal(out[7]["img"], imgs[7] * 2)
    # column extraction stacks back into one ndarray
    stacked = ds.to_numpy(column="img")
    assert stacked.shape == (10, 4, 3)
    np.testing.assert_array_equal(stacked, imgs)


def test_tensor_array_zero_copy_semantics():
    arr = np.random.default_rng(0).random((6, 2, 2))
    ta = tensor_ext.ArrowTensorArray.from_numpy(arr)
    back = ta.to_numpy_tensor()
    np.testing.assert_array_equal(back, arr)
    # serialize through arrow IPC and back (the extension registers)
    import pyarrow as pa

    t = pa.Table.from_arrays([ta], names=["x"])
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    t2 = pa.ipc.open_stream(sink.getvalue()).read_all()
    np.testing.assert_array_equal(
        t2.column("x").combine_chunks().to_numpy_tensor(), arr)
