"""Serve HTTP ingress, long-poll push, autoscaling, multiplexing.

Reference test models: serve/tests/test_http_routes.py,
test_long_poll.py, test_autoscaling_policy.py, test_multiplex.py.
"""

import http.client
import json
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster
from ray_tpu.serve.long_poll import LongPollHost


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    serve.start()
    yield c
    serve.shutdown()
    c.shutdown()


def _http(addr, method, path, body=None):
    conn = http.client.HTTPConnection(*addr, timeout=60)
    payload = json.dumps(body) if body is not None else None
    conn.request(method, path, body=payload)
    resp = conn.getresponse()
    data = json.loads(resp.read())
    conn.close()
    return resp.status, data


# ---------------- long-poll host unit ----------------

def test_long_poll_host_basics():
    h = LongPollHost()
    assert h.poll({"k": 0}, timeout=0.05) == {}
    h.set("k", "v1")
    out = h.poll({"k": 0}, timeout=0.0)
    assert out == {"k": (1, "v1")}
    # blocked poll wakes on set
    import threading

    got = {}

    def waiter():
        got.update(h.poll({"k": 1}, timeout=5.0))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    h.set("k", "v2")
    t.join(5)
    assert got == {"k": (2, "v2")}


# ---------------- HTTP ingress ----------------

def test_http_proxy_routes(cluster):
    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, req):
            return {"echo": req}

    @serve.deployment(route_prefix="/math/double")
    class Double:
        def __call__(self, req):
            return {"doubled": 2 * int(req["x"])}

    serve.run(Echo, name="echo")
    serve.run(Double, name="double")
    addr = serve.start_http_proxy()
    deadline = time.monotonic() + 30
    while True:  # proxy learns routes via long-poll; wait for the push
        status, data = _http(addr, "GET", "/echo?who=tpu")
        if status == 200 or time.monotonic() > deadline:
            break
        time.sleep(0.25)
    assert status == 200 and data == {"echo": {"who": "tpu"}}

    status, data = _http(addr, "POST", "/echo", {"a": [1, 2]})
    assert status == 200 and data == {"echo": {"a": [1, 2]}}

    status, data = _http(addr, "GET", "/math/double?x=21")
    assert status == 200 and data == {"doubled": 42}

    status, data = _http(addr, "GET", "/nope")
    assert status == 404


def test_http_proxy_500_on_user_error(cluster):
    @serve.deployment(route_prefix="/boom")
    class Boom:
        def __call__(self, req):
            raise RuntimeError("kapow")

    serve.run(Boom, name="boom")
    addr = serve.start_http_proxy()
    deadline = time.monotonic() + 30
    while True:
        status, data = _http(addr, "GET", "/boom")
        if status != 404 or time.monotonic() > deadline:
            break
        time.sleep(0.25)
    assert status == 500 and "kapow" in data["error"]


# ---------------- autoscaling ----------------

def test_autoscaling_scales_up_and_down(cluster):
    @serve.deployment
    class Slow:
        def __call__(self, req):
            time.sleep(0.4)
            return "ok"

    h = serve.run(
        Slow.options(
            autoscaling_config={
                "min_replicas": 1,
                "max_replicas": 3,
                "target_num_ongoing_requests_per_replica": 2,
            },
            max_concurrent_queries=4,
        ),
        name="slow",
    )
    c = ray_tpu.get_actor("__serve_controller__")

    def replica_count():
        return ray_tpu.get(
            c.list_deployments.remote(), timeout=30
        )["slow"]["num_replicas"]

    assert replica_count() == 1
    # sustained burst -> scale up
    refs = []
    deadline = time.monotonic() + 25
    scaled_up = False
    while time.monotonic() < deadline:
        refs.extend(h.remote(i) for i in range(8))
        ray_tpu.wait(refs, num_returns=min(4, len(refs)), timeout=5)
        if replica_count() >= 2:
            scaled_up = True
            break
    assert scaled_up, "never scaled past 1 replica"
    ray_tpu.get(refs, timeout=120)
    # idle -> back down to min
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and replica_count() > 1:
        time.sleep(0.5)
    assert replica_count() == 1


# ---------------- multiplexing ----------------

def test_multiplexed_lru_and_context(cluster):
    @serve.deployment(num_replicas=1)
    class MultiModel:
        def __init__(self):
            self.loads = []

            @serve.multiplexed(max_num_models_per_replica=2)
            def load(mid):
                self.loads.append(mid)
                return {"model": mid}

            self._load = load

        def __call__(self, req):
            mid = serve.get_multiplexed_model_id()
            model = self._load(mid)
            return {"served_by": model["model"], "loads": list(self.loads)}

    h = serve.run(MultiModel, name="mm")
    r1 = ray_tpu.get(
        h.options(multiplexed_model_id="m1").remote({}), timeout=60
    )
    assert r1["served_by"] == "m1"
    r2 = ray_tpu.get(
        h.options(multiplexed_model_id="m1").remote({}), timeout=60
    )
    assert r2["loads"].count("m1") == 1  # cached, not reloaded
    ray_tpu.get(h.options(multiplexed_model_id="m2").remote({}), timeout=60)
    ray_tpu.get(h.options(multiplexed_model_id="m3").remote({}), timeout=60)
    r4 = ray_tpu.get(
        h.options(multiplexed_model_id="m1").remote({}), timeout=60
    )
    # m1 was evicted by the 2-model LRU when m2+m3 loaded -> reloaded
    assert r4["loads"].count("m1") == 2


def test_redeploy_pushes_to_handles(cluster):
    @serve.deployment
    class V:
        def __init__(self, tag="v1"):
            self.tag = tag

        def __call__(self, req):
            return self.tag

    h = serve.run(V, name="vers", version="1")
    assert ray_tpu.get(h.remote({}), timeout=60) == "v1"
    serve.run(V, name="vers", init_args=("v2",), version="2")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            if ray_tpu.get(h.remote({}), timeout=30) == "v2":
                break
        except Exception:
            pass  # window where old replicas are draining
        time.sleep(0.25)
    assert ray_tpu.get(h.remote({}), timeout=30) == "v2"
