"""Owner-lease liveness wedge regression (ROADMAP "pre-existing").

The wedge: a direct lease push (`CoreWorker._lease_push`) is an UNACKED
fire — a frame lost in the write path (connection torn down between the
buffer append and the flush, or an async write error swallowed by fire
semantics) left the task recorded as in-flight on a lease forever. The
agent, told about the task via lease_tasks_started, kept extending the
lease for a task that would never run: a whole round of tasks sat
leased while the pool idled, until the per-test 600s watchdog — only
killing the worker (lease_revoked failover) unwedged it.

The fix under test: the lease liveness pump probes the leased worker
over the SAME connection the push used (`probe_tasks`; the worker
records every task id at frame ingress). TCP FIFO + in-order frame
dispatch make the probe reply a delivery barrier, so "unknown" proves
the push was lost and the owner can fail it over through the queue
with no double-execution risk. These tests inject exactly that loss
(`worker.lease_push` drop site) and require recovery in seconds, not
watchdog timeouts.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu._private import config as cfg
from ray_tpu._private import fault_injection
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    old = {"worker_lease_probe_s": cfg.get("worker_lease_probe_s")}
    cfg.set_system_config({"worker_lease_probe_s": 0.5})
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()
    cfg.set_system_config(old)


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    fault_injection.clear()


@ray_tpu.remote(num_cpus=0)
def _double(x):
    return x * 2


def test_lost_lease_push_recovers_without_watchdog(cluster):
    """Drop a burst of execute_task pushes mid-stream: every task must
    still complete, via probe-driven failover, well under the 600s
    watchdog the wedge used to hit."""
    # warm the lease path so pushes ride cached leases
    assert ray_tpu.get([_double.remote(i) for i in range(20)],
                       timeout=60) == [2 * i for i in range(20)]

    fault_injection.configure({"site": "worker.lease_push",
                               "action": "drop", "after": 3,
                               "count": 8})
    t0 = time.monotonic()
    out = ray_tpu.get([_double.remote(i) for i in range(200)],
                      timeout=120)
    dt = time.monotonic() - t0
    assert out == [2 * i for i in range(200)]
    hits = [h for h in fault_injection.hits()
            if h["site"] == "worker.lease_push"]
    assert len(hits) == 8, f"expected 8 dropped pushes, saw {len(hits)}"
    # the old failure mode was a 600s stall; probe failover is ~probe_s
    assert dt < 60, f"recovery took {dt:.1f}s — wedge is back"


def test_shuffle_streaming_repro_loop(cluster):
    """The original repro surface, scaled down and looped in-process:
    shuffle-style (sort/groupby: many small tasks + object exchange)
    and streaming-style (iter_batches over a pipelined map) workloads,
    with lease pushes being lost throughout. ~1-in-3 runs of the full
    suite used to wedge; each loop here must finish inside a hard
    deadline far below the watchdog."""
    fault_injection.configure({"site": "worker.lease_push",
                               "action": "drop", "after": 10,
                               "count": 12})
    rng = np.random.default_rng(0)
    deadline = time.monotonic() + 240  # vs the 600s watchdog PER test
    for _ in range(3):
        vals = rng.integers(0, 10_000, 300).tolist()
        ds = rdata.from_items(vals, parallelism=6).sort()
        assert list(ds.iter_rows()) == sorted(vals)

        rows = [{"k": i % 5, "v": i} for i in range(150)]
        counts = dict(rdata.from_items(rows, parallelism=5)
                      .groupby("k").count().iter_rows())
        assert counts == {k: 30 for k in range(5)}

        got = []
        for batch in (rdata.from_items(list(range(120)), parallelism=6)
                      .map(lambda x: x + 1)
                      .iter_batches(prefetch_batches=2)):
            got.extend(batch)
        assert sorted(got) == list(range(1, 121))
        assert time.monotonic() < deadline, (
            "shuffle/streaming loop exceeded its deadline — the "
            "owner-lease liveness wedge has regressed")


def test_probe_tasks_reports_known_tids(cluster):
    """The worker-side half of the barrier: ids of delivered tasks stay
    probe-visible (bounded ring), unknown ids don't."""
    from ray_tpu._private.api import _get_worker

    assert ray_tpu.get(_double.remote(21), timeout=60) == 42
    w = _get_worker()
    with w._lease_lock:
        leases = [l for e in w._lease_cache.values()
                  for l in e["leases"]]
    if not leases:  # lease path disabled/reclaimed: nothing to probe
        pytest.skip("no live lease to probe")
    addr = (leases[0]["addr"], leases[0]["port"])
    cli = w._peer_clients.get(addr)
    assert cli is not None
    res = cli.call("probe_tasks", {"task_ids": [b"\x00" * 16]},
                   timeout=10)
    assert res["known"] == []
