"""runtime_env plugin API + pip plugin (reference
python/ray/_private/runtime_env/plugin.py + pip.py).

The e2e test hand-crafts a wheel (zero-egress image: no PyPI) and runs a
task whose venv has a package the driver does not."""

import os
import zipfile

import pytest

import ray_tpu
from ray_tpu._private import runtime_env_plugins as rep
from ray_tpu._private.runtime_env import PackageCache
from ray_tpu.cluster_utils import Cluster

PKG = "graftpkg"
VERSION = "0.1.0"


def _craft_wheel(dirpath: str) -> str:
    """A minimal valid py3-none-any wheel, built by hand."""
    name = f"{PKG}-{VERSION}-py3-none-any.whl"
    path = os.path.join(dirpath, name)
    di = f"{PKG}-{VERSION}.dist-info"
    files = {
        f"{PKG}/__init__.py": f"__version__ = {VERSION!r}\n",
        f"{di}/METADATA": (
            f"Metadata-Version: 2.1\nName: {PKG}\nVersion: {VERSION}\n"
        ),
        f"{di}/WHEEL": (
            "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true\n"
            "Tag: py3-none-any\n"
        ),
    }
    record = "".join(f"{fn},,\n" for fn in files) + f"{di}/RECORD,,\n"
    files[f"{di}/RECORD"] = record
    with zipfile.ZipFile(path, "w") as z:
        for fn, content in files.items():
            z.writestr(fn, content)
    return path


def test_pip_uri_deterministic_and_config_sensitive():
    p = rep.PipPlugin()
    u1 = p.uri_for(["a==1", "b"])
    assert u1.startswith("pip://")
    assert p.uri_for(["b", "a==1"]) == u1  # order-insensitive
    assert p.uri_for(["a==2", "b"]) != u1
    assert p.uri_for({"packages": ["a==1", "b"],
                      "install_options": ["--no-index"]}) != u1
    with pytest.raises(ValueError):
        p.uri_for("not-a-list")


def test_package_cache_gc_evicts_plugin_uris(tmp_path):
    """Idle plugin URIs share the pkg:// cache lifecycle: beyond the
    keep cap, oldest-idle venv dirs are deleted from disk."""
    from ray_tpu._private import runtime_env as re_mod

    cache = PackageCache(str(tmp_path))
    uris = [f"pip://{i:032x}" for i in range(re_mod.IDLE_CACHE_KEEP + 2)]
    for u in uris:
        os.makedirs(cache.dir_for(u))
        cache.acquire(u)
    for u in uris:
        cache.release(u)
    alive = [u for u in uris if os.path.isdir(cache.dir_for(u))]
    assert len(alive) == re_mod.IDLE_CACHE_KEEP
    # the survivors are the newest-idle ones
    assert alive == uris[-re_mod.IDLE_CACHE_KEEP:]


@pytest.mark.slow  # ~10s venv build; uri/cache/failure-path tests keep tier-1 coverage
def test_pip_env_task_runs_package_driver_lacks(tmp_path):
    with pytest.raises(ImportError):
        import graftpkg  # noqa: F401 — the driver must NOT have it

    wheel_dir = str(tmp_path)
    _craft_wheel(wheel_dir)
    env = {"pip": {"packages": [PKG],
                   "install_options": ["--no-index", "--find-links",
                                       wheel_dir]}}
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    try:
        @ray_tpu.remote(runtime_env=env)
        def use_pkg():
            import graftpkg

            return graftpkg.__version__, os.environ.get("VIRTUAL_ENV")

        version, venv = ray_tpu.get(use_pkg.remote(), timeout=300)
        assert version == VERSION
        assert venv and "pip/" in venv.replace(os.sep, "/")
        # node-level cache: the venv dir exists under the agent cache
        uri = rep.PipPlugin().uri_for(env["pip"])
        dest = c.head_agent.pkg_cache.dir_for(uri)
        assert os.path.isdir(dest)
        # second task with the SAME env reuses the cached venv (same
        # VIRTUAL_ENV path, no rebuild — dir mtime unchanged)
        mtime = os.path.getmtime(dest)
        version2, venv2 = ray_tpu.get(use_pkg.remote(), timeout=120)
        assert (version2, venv2) == (version, venv)
        assert os.path.getmtime(dest) == mtime
    finally:
        c.shutdown()


def test_bad_pip_env_fails_task_and_frees_resources():
    """A plugin create error must FAIL the task (no hang) and leave the
    node's resources and URI refcounts clean for the next task."""
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    try:
        env = {"pip": {"packages": ["no-such-pkg-xyz-12345"],
                       "install_options": ["--no-index"]}}

        @ray_tpu.remote(runtime_env=env, max_retries=0)
        def doomed():
            return 1

        with pytest.raises(ray_tpu.RayTaskError, match="spawn failed"):
            ray_tpu.get(doomed.remote(), timeout=300)
        # refcounts did not leak: the failed env's URI is not pinned
        uri = rep.PipPlugin().uri_for(env["pip"])
        assert c.head_agent.pkg_cache._refs.get(uri) is None
        # and the node still runs ordinary tasks (resources were freed)
        @ray_tpu.remote(num_cpus=2)
        def fine():
            return 42

        assert ray_tpu.get(fine.remote(), timeout=120) == 42
    finally:
        c.shutdown()


class _StampPlugin(rep.RuntimeEnvPlugin):
    name = "stamp"
    priority = 50

    def uri_for(self, config):
        return "stamp://" + rep._config_digest(config)

    def create(self, uri, config, dest):
        os.makedirs(dest + ".tmp", exist_ok=True)
        os.replace(dest + ".tmp", dest)

    def modify_context(self, uri, config, dest, ctx):
        ctx.env["GRAFT_STAMP"] = str(config)


def test_custom_plugin_modifies_worker_env():
    rep.register_plugin(_StampPlugin())
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    try:
        @ray_tpu.remote(runtime_env={"stamp": "xyz"})
        def read_stamp():
            return os.environ.get("GRAFT_STAMP")

        assert ray_tpu.get(read_stamp.remote(), timeout=120) == "xyz"
    finally:
        c.shutdown()
        rep.registry().pop("stamp", None)


def test_python_version_env_runs_other_interpreter():
    """The conda-equivalent plugin (VERDICT r4 item 10): a task runs
    under a DIFFERENT CPython minor than the driver, through the same
    refcounted URI cache; the venv is built once and reused."""
    import sys

    driver_minor = "%d.%d" % sys.version_info[:2]
    other = next(
        (v for v in ("3.11", "3.10", "3.13")
         if v != driver_minor
         and rep.PyVersionPlugin.find_interpreter(v)),
        None)
    if other is None:
        pytest.skip("no second CPython minor installed on this host")

    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    try:
        @ray_tpu.remote(num_cpus=0,
                        runtime_env={"python_version": other})
        def interp_version():
            import sys as _s
            # builtins on purpose: source-shipped functions recompile
            # with synthetic globals that must still resolve them
            parts = [str(x) for x in list(_s.version_info[:2])]
            return ".".join(parts) if len(parts) == 2 else "?"

        got = ray_tpu.get(interp_version.remote(), timeout=240)
        assert got == other != driver_minor

        # cached: the SECOND task reuses the materialized venv — the
        # cache dir for the uri exists exactly once and survives
        uri = rep.PyVersionPlugin().uri_for(other)
        assert ray_tpu.get(interp_version.remote(), timeout=240) == other
        agent = c.head_agent
        assert agent.pkg_cache.dir_if_present(uri) is not None
    finally:
        c.shutdown()


def test_python_version_uri_and_venv_materialization(tmp_path):
    """URI is deterministic per version; create() builds a runnable
    venv of the requested minor (the cache GC lifecycle for plugin
    URIs is covered by test_package_cache_gc_evicts_plugin_uris)."""
    import subprocess

    plug = rep.PyVersionPlugin()
    assert plug.uri_for("3.11") == plug.uri_for("3.11")
    assert plug.uri_for("3.11") != plug.uri_for("3.10")
    with pytest.raises(ValueError):
        plug.uri_for("evil; rm -rf /")

    other = next(
        (v for v in ("3.11", "3.10")
         if plug.find_interpreter(v)), None)
    if other is None:
        pytest.skip("no second CPython minor installed on this host")
    dest = os.path.join(str(tmp_path), "venv")
    plug.create(plug.uri_for(other), other, dest)
    py = os.path.join(dest, "bin", "python")
    out = subprocess.run(
        [py, "-c", "import sys; print('%d.%d' % sys.version_info[:2])"],
        capture_output=True, text=True, timeout=60)
    assert out.stdout.strip() == other
