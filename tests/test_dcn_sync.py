"""Cross-slice gradient sync (train.dcn) over a 2-"slice" test cluster.

Each WorkerGroup worker stands in for one slice's representative host;
`dcn_allreduce_grads` must produce gradients identical to a single-group
reduction (within codec tolerance for int8). The error-feedback
convergence property itself is covered in test_collective_ring.py.
"""

import sys

import cloudpickle
import numpy as np
import pytest

from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.worker_group import WorkerGroup

# worker subprocesses can't import the tests package: ship the helper
# functions by value
cloudpickle.register_pickle_by_value(sys.modules[__name__])

SLICES = 2


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.fixture
def gang(cluster):
    wg = WorkerGroup(SLICES, resources_per_worker={"CPU": 1})
    yield wg
    wg.shutdown()


def _slice_grads(rank: int) -> dict:
    """Deterministic per-slice gradient pytree (as if each slice had
    already psum'd over its own ICI mesh)."""
    rng = np.random.default_rng(100 + rank)
    return {
        "dense": {"w": rng.standard_normal((32, 16)).astype(np.float32),
                  "b": rng.standard_normal(16).astype(np.float32)},
        "emb": rng.standard_normal((64, 8)).astype(np.float32),
    }


def _sync_on_worker(worker, group_name, codec, bucket_bytes):
    from ray_tpu.train import dcn_allreduce_grads

    grads = _slice_grads(worker.worker_idx)
    return dcn_allreduce_grads(grads, group_name, codec=codec,
                               bucket_bytes=bucket_bytes)


def _reference_mean():
    import jax

    trees = [_slice_grads(r) for r in range(SLICES)]
    return jax.tree_util.tree_map(
        lambda *xs: np.mean(np.stack(xs), axis=0), *trees)


def test_dcn_allreduce_grads_matches_single_group(gang):
    group = gang.init_collective()
    outs = gang.execute(_sync_on_worker, group, None, 1024, timeout=120)
    ref = _reference_mean()
    import jax

    for synced in outs:
        flat_s = jax.tree_util.tree_leaves(synced)
        flat_r = jax.tree_util.tree_leaves(ref)
        assert len(flat_s) == len(flat_r)
        for s, r in zip(flat_s, flat_r):
            assert s.shape == r.shape and s.dtype == r.dtype
            np.testing.assert_allclose(s, r, rtol=1e-6, atol=1e-6)
    # both slices got bit-identical gradients (lockstep guarantee)
    for s0, s1 in zip(jax.tree_util.tree_leaves(outs[0]),
                      jax.tree_util.tree_leaves(outs[1])):
        np.testing.assert_array_equal(s0, s1)


def test_dcn_allreduce_grads_int8_within_tolerance(gang):
    group = gang.init_collective()
    outs = gang.execute(_sync_on_worker, group, "int8", 4096, timeout=120)
    ref = _reference_mean()
    import jax

    for synced in outs:
        for s, r in zip(jax.tree_util.tree_leaves(synced),
                        jax.tree_util.tree_leaves(ref)):
            # one quantized hop per partial: error bounded by block scale
            np.testing.assert_allclose(s, r, rtol=0.05, atol=0.05)


def test_destroyed_group_name_is_reusable(gang):
    """Re-initializing a collective group under the SAME name after
    destroy must work: destroy purges stale mailbox frames, seq counters,
    and the KV rendezvous entries (the leak this pins)."""
    name = "reuse-me"
    gang.init_collective(name)
    outs1 = gang.execute(_sync_on_worker, name, None, 1024, timeout=120)
    gang.destroy_collective()
    gang.init_collective(name)
    outs2 = gang.execute(_sync_on_worker, name, None, 1024, timeout=120)
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(outs1[0]),
                    jax.tree_util.tree_leaves(outs2[0])):
        np.testing.assert_array_equal(a, b)
