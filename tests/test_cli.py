"""CLI end-to-end: start a real head process, join a node, connect a
remote driver, submit a script (reference scripts.py `ray start/submit`).
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest


@pytest.fixture
def head_proc():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts", "start", "--head",
         "--resources", '{"CPU": 4, "memory": 2147483648}'],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd="/root/repo",
    )
    address = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        m = re.search(r"--address (\S+)", line or "")
        if m:
            address = m.group(1)
            break
    assert address, "head never printed its address"
    yield proc, address
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_cli_head_connect_and_run(head_proc):
    _, address = head_proc
    import ray_tpu

    ray_tpu.init(address=address)
    try:

        @ray_tpu.remote
        def add(a, b):
            return a + b

        assert ray_tpu.get(add.remote(20, 22), timeout=60) == 42
        assert ray_tpu.cluster_resources().get("CPU") == 4
    finally:
        ray_tpu.shutdown()


def test_cli_submit(head_proc, tmp_path):
    _, address = head_proc
    script = tmp_path / "driver.py"
    script.write_text(
        "import os\n"
        "import ray_tpu\n"
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS'])\n"
        "@ray_tpu.remote\n"
        "def f():\n"
        "    return 'submitted-ok'\n"
        "print(ray_tpu.get(f.remote(), timeout=60))\n"
        "ray_tpu.shutdown()\n"
    )
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "submit",
         "--address", address, str(script)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd="/root/repo",
    )
    assert "submitted-ok" in out.stdout, out.stdout + out.stderr


def test_cli_list_state(head_proc):
    _, address = head_proc
    import ray_tpu

    ray_tpu.init(address=address)
    try:
        @ray_tpu.remote
        def noop():
            return 1

        ray_tpu.get(noop.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def listing(kind):
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", "list", kind,
             "--address", address],
            capture_output=True, text=True, timeout=120, env=env,
            cwd="/root/repo",
        )
        assert out.returncode == 0, out.stderr
        return out.stdout

    assert "resources_total" in listing("nodes")
    assert "job_id" in listing("jobs")
    deadline = time.time() + 20
    while time.time() < deadline:  # task events flush asynchronously
        if "noop" in listing("tasks"):
            break
        time.sleep(1.0)
    assert "noop" in listing("tasks")


def test_cluster_up_down_dry_run(tmp_path, capsys):
    """`up`/`down` launcher CLI over the GCP TPU provider in dry-run
    (reference `ray up/down` + autoscaler/gcp/tpu.yaml, scaled)."""
    import json as _json

    from ray_tpu.scripts import main as cli_main

    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(
        "cluster_name: testtpu\n"
        "provider:\n"
        "  type: gcp_tpu\n"
        "  project: proj-x\n"
        "  zone: us-central2-b\n"
        "head_address: 10.0.0.9:6379\n"
        "min_workers: 2\n"
        "node_type: tpu-v5e-8\n"
    )
    cli_main(["up", str(cfg), "--dry-run"])
    out = _json.loads(capsys.readouterr().out)
    assert len(out["launched"]) == 2
    assert all(n.startswith("ray-tpu-tpu-v5e-8-") for n in out["launched"])
    cmds = out["dry_run_commands"]
    assert len(cmds) == 2
    assert all("tpu-vm create" in c and "--zone=us-central2-b" in c
               for c in cmds)
    assert all("ray-tpu-head=10.0.0.9:6379" in c for c in cmds)

    cli_main(["down", str(cfg), "--dry-run", "--nodes",
              out["launched"][0]])
    out2 = _json.loads(capsys.readouterr().out)
    assert out2["terminated"] == [out["launched"][0]]
    assert "delete" in out2["dry_run_commands"][0]


def test_cli_list_events_via_cli(head_proc, capsys):
    """`list events` goes through the actual CLI branch."""
    import json as _json

    from ray_tpu.scripts import main as cli_main

    _, address = head_proc
    cli_main(["list", "events", "--address", address, "--limit", "50"])
    rows = _json.loads(capsys.readouterr().out)
    assert any(e["kind"] == "NODE_ADDED" for e in rows)


def test_cluster_down_default_dry_run(tmp_path, capsys):
    """`down` without --nodes consults the provider's LIVE listing; in
    dry-run the list command is recorded (never a silent no-op)."""
    import json as _json

    from ray_tpu.scripts import main as cli_main

    cfg = tmp_path / "c.yaml"
    cfg.write_text(
        "provider:\n  type: gcp_tpu\n  project: p\n  zone: z\n")
    cli_main(["down", str(cfg), "--dry-run"])
    out = _json.loads(capsys.readouterr().out)
    assert out["terminated"] == []
    assert any("list" in c and "--filter=name~^ray-tpu-" in c
               for c in out["dry_run_commands"])
