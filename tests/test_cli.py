"""CLI end-to-end: start a real head process, join a node, connect a
remote driver, submit a script (reference scripts.py `ray start/submit`).
"""

import json
import os
import re
import signal
import subprocess
import sys
import time

import pytest


@pytest.fixture
def head_proc():
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.scripts", "start", "--head",
         "--resources", '{"CPU": 4, "memory": 2147483648}'],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd="/root/repo",
    )
    address = None
    deadline = time.time() + 60
    while time.time() < deadline:
        line = proc.stdout.readline()
        m = re.search(r"--address (\S+)", line or "")
        if m:
            address = m.group(1)
            break
    assert address, "head never printed its address"
    yield proc, address
    proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def test_cli_head_connect_and_run(head_proc):
    _, address = head_proc
    import ray_tpu

    ray_tpu.init(address=address)
    try:

        @ray_tpu.remote
        def add(a, b):
            return a + b

        assert ray_tpu.get(add.remote(20, 22), timeout=60) == 42
        assert ray_tpu.cluster_resources().get("CPU") == 4
    finally:
        ray_tpu.shutdown()


def test_cli_submit(head_proc, tmp_path):
    _, address = head_proc
    script = tmp_path / "driver.py"
    script.write_text(
        "import os\n"
        "import ray_tpu\n"
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS'])\n"
        "@ray_tpu.remote\n"
        "def f():\n"
        "    return 'submitted-ok'\n"
        "print(ray_tpu.get(f.remote(), timeout=60))\n"
        "ray_tpu.shutdown()\n"
    )
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts", "submit",
         "--address", address, str(script)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd="/root/repo",
    )
    assert "submitted-ok" in out.stdout, out.stdout + out.stderr


def test_cli_list_state(head_proc):
    _, address = head_proc
    import ray_tpu

    ray_tpu.init(address=address)
    try:
        @ray_tpu.remote
        def noop():
            return 1

        ray_tpu.get(noop.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def listing(kind):
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts", "list", kind,
             "--address", address],
            capture_output=True, text=True, timeout=120, env=env,
            cwd="/root/repo",
        )
        assert out.returncode == 0, out.stderr
        return out.stdout

    assert "resources_total" in listing("nodes")
    assert "job_id" in listing("jobs")
    deadline = time.time() + 20
    while time.time() < deadline:  # task events flush asynchronously
        if "noop" in listing("tasks"):
            break
        time.sleep(1.0)
    assert "noop" in listing("tasks")
