"""Elastic fault tolerance of the DCN collective stack.

Covers the abort → heal/reform → resume cycle end to end:

- a rank killed mid-allreduce (deterministic fault injection) makes the
  SURVIVORS raise CollectiveAbortError within the abort-detection
  interval — well under RAY_TPU_COLLECTIVE_TIMEOUT_S;
- `reform_group` (via the driver's `WorkerGroup.reform_collective`)
  rebuilds the ring under a bumped epoch, and a resumed training step
  produces gradients matching a clean run at the surviving world size;
- a 2-slice DCN job resumes from checkpoint at reduced then restored
  world size (shrink → grow elasticity);
- frames from an old incarnation are provably rejected at mailbox
  ingress; abort frames wake blocked recvs; error-feedback residuals
  are dropped across a reform and cannot corrupt post-reform numerics.
"""

import asyncio
import sys
import threading
import time
from types import SimpleNamespace

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.worker_group import WorkerGroup

# worker subprocesses can't import the tests package: ship helpers by value
cloudpickle.register_pickle_by_value(sys.modules[__name__])

DIM = 8
LR = 0.1


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


# ---------------------------------------------------------------------------
# worker-side helpers (shipped by value)
# ---------------------------------------------------------------------------


def _survivor_allreduce(worker, group):
    """Run an allreduce with a LONG timeout; report how fast (and how)
    it failed. The abort must beat the timeout by an order of
    magnitude."""
    from ray_tpu.collective import CollectiveAbortError, allreduce

    t0 = time.monotonic()
    try:
        out = allreduce(np.ones(256, np.float32), group, timeout=60.0)
        return {"aborted": False, "sum": float(np.asarray(out).sum())}
    except CollectiveAbortError as e:
        return {"aborted": True, "elapsed": time.monotonic() - t0,
                "group": e.group, "rank": e.rank, "epoch": e.epoch,
                "op": e.op, "msg": str(e)}


def _victim_allreduce(worker, group):
    """Configure a deterministic kill (hard process exit at this rank's
    first ring chunk send) and walk into it."""
    from ray_tpu._private import fault_injection
    from ray_tpu.collective import allreduce

    fault_injection.configure([{
        "site": "ring.send", "match": {"rank": 1, "step": 0, "chunk": 0},
        "action": "exit",
    }])
    return allreduce(np.ones(256, np.float32), group, timeout=60.0)


def _member_reform(worker, group, world, rank):
    """SPMD-side reform (no driver-chosen epoch): a survivor bumps the
    epoch channel; a respawned member adopts it (migrating if it read a
    stale value first)."""
    from ray_tpu.collective import reform_group

    g = reform_group(world, rank, group)
    return {"epoch": g.epoch, "rank": g.rank}


def _plain_allreduce(worker, group, value):
    from ray_tpu.collective import allreduce
    from ray_tpu.collective.collective import _groups

    out = allreduce(np.full(4, float(value), np.float32), group,
                    timeout=60.0)
    return {"out": np.asarray(out).tolist(), "epoch": _groups[group].epoch}


def _grad(rank, step):
    rng = np.random.default_rng(1000 * (rank + 1) + step)
    return rng.standard_normal(DIM).astype(np.float32)


def _train_steps(worker, group, rank, start, n, params, kill_at=None):
    """SGD over dcn-synced mean gradients; deterministic per (rank,
    step). kill_at=N hard-kills this rank at its Nth ring chunk send."""
    from ray_tpu._private import fault_injection
    from ray_tpu.train import dcn_allreduce_grads

    p = np.asarray(params, np.float32).copy()
    if kill_at is not None:
        fault_injection.configure([{
            "site": "ring.send", "match": {"rank": rank},
            "after": kill_at, "action": "exit",
        }])
    for s in range(start, start + n):
        synced = dcn_allreduce_grads({"p": _grad(rank, s)}, group,
                                     timeout=60.0)["p"]
        p = p - LR * synced
    return p


def _train_steps_expect_abort(worker, group, rank, start, n, params):
    from ray_tpu.collective import CollectiveAbortError

    t0 = time.monotonic()
    try:
        out = _train_steps(worker, group, rank, start, n, params)
        return {"aborted": False, "params": out}
    except CollectiveAbortError as e:
        return {"aborted": True, "elapsed": time.monotonic() - t0,
                "epoch": e.epoch, "op": e.op}


# ---------------------------------------------------------------------------
# cluster tests: kill → fast abort → heal → reform → resume
# ---------------------------------------------------------------------------


@pytest.mark.slow  # ~16s; two-slice resume e2e + abort-frame units keep tier-1 coverage
def test_mid_op_kill_aborts_survivor_fast_then_reforms(cluster):
    """Acceptance: a rank killed mid-allreduce under fault injection
    makes the surviving rank raise CollectiveAbortError well under the
    60s collective timeout; heal() + reform_collective() then restore a
    working group under a bumped epoch."""
    wg = WorkerGroup(2, resources_per_worker={"CPU": 1}, max_restarts=1)
    try:
        group = wg.init_collective()
        refs = [wg.workers[0].execute.remote(_survivor_allreduce, group),
                wg.workers[1].execute.remote(_victim_allreduce, group)]
        surv = ray_tpu.get(refs[0], timeout=90)
        assert surv["aborted"], f"survivor completed?! {surv}"
        # well under the 60s timeout (observed ~50ms via peer-loss
        # detection; 20s leaves headroom for a loaded CI box)
        assert surv["elapsed"] < 20.0, surv
        # the typed error names group/rank/epoch/op
        assert surv["group"] == group and surv["rank"] == 0
        assert surv["epoch"] == 1 and surv["op"].startswith("ar:")
        assert "rank 1" in surv["msg"]

        # the victim's own ref must not return a value (its process died)
        with pytest.raises(Exception):
            ray_tpu.get(refs[1], timeout=15)

        # heal: actor-level max_restarts respawns the dead rank; reform
        # re-rendezvouses under a bumped epoch — exercised here through
        # the SPMD member path (survivor bumps the epoch channel, the
        # respawned fresh process adopts it); the group works again
        assert wg.heal(wait_restart_s=90) == 2
        refs = [w.execute.remote(_member_reform, group, 2, r)
                for r, w in enumerate(wg.workers)]
        reformed = ray_tpu.get(refs, timeout=120)
        assert reformed[0]["epoch"] == reformed[1]["epoch"] >= 2
        outs = wg.execute(_plain_allreduce, group, 1.0, timeout=90)
        for o in outs:
            assert o["out"] == [2.0, 2.0, 2.0, 2.0]
            assert o["epoch"] >= 2  # bumped incarnation
    finally:
        wg.shutdown()


def test_two_slice_job_resumes_reduced_then_restored(cluster, tmp_path):
    """Acceptance: a 2-slice DCN job survives losing a slice (resume
    from checkpoint at world 1) and regaining it (resume at world 2);
    post-reform gradients match a clean run at each world size."""
    # bit-exact reference schedule (f32 ring sums are order-stable)
    p = np.zeros(DIM, np.float32)
    for s in range(2):
        p = p - LR * ((_grad(0, s) + _grad(1, s)) / 2)
    ref_ck1 = p.copy()
    for s in range(2, 4):
        p = p - LR * _grad(0, s)
    ref_ck2 = p.copy()
    for s in range(4, 6):
        p = p - LR * ((_grad(0, s) + _grad(1, s)) / 2)
    ref_final = p.copy()

    mgr = CheckpointManager(str(tmp_path / "ck"))
    wg = WorkerGroup(2, resources_per_worker={"CPU": 1}, max_restarts=0)
    try:
        group = wg.init_collective()
        p0 = np.zeros(DIM, np.float32)

        # steps 0-1 at world 2, checkpoint
        refs = [w.execute.remote(_train_steps, group, r, 0, 2, p0)
                for r, w in enumerate(wg.workers)]
        outs = ray_tpu.get(refs, timeout=120)
        np.testing.assert_allclose(outs[0], ref_ck1, rtol=1e-6)
        np.testing.assert_array_equal(outs[0], outs[1])  # lockstep
        mgr.register(Checkpoint.from_dict(
            {"step": 2, "params": outs[0]}, mgr.next_dir()))

        # step 2 attempt: rank 1 hard-dies mid-allreduce; rank 0 aborts
        # fast and applies NO partial update
        refs = [wg.workers[0].execute.remote(
                    _train_steps_expect_abort, group, 0, 2, 1, outs[0]),
                wg.workers[1].execute.remote(
                    _train_steps, group, 1, 2, 1, outs[1], 0)]
        surv = ray_tpu.get(refs[0], timeout=90)
        assert surv["aborted"] and surv["elapsed"] < 20.0, surv

        # shrink to the surviving world, reform, resume from checkpoint
        assert wg.heal(wait_restart_s=5) == 1  # max_restarts=0: drop
        wg.reform_collective()
        ck = mgr.latest_dict()
        assert ck["step"] == 2
        out = ray_tpu.get(wg.workers[0].execute.remote(
            _train_steps, group, 0, ck["step"], 2, ck["params"]),
            timeout=120)
        np.testing.assert_allclose(out, ref_ck2, rtol=1e-6)
        mgr.register(Checkpoint.from_dict(
            {"step": 4, "params": out}, mgr.next_dir()))

        # regain the slice: grow back to world 2, reform, resume
        assert wg.grow(2) == 2
        wg.reform_collective()
        ck = mgr.latest_dict()
        assert ck["step"] == 4
        refs = [w.execute.remote(
                    _train_steps, group, r, ck["step"], 2, ck["params"])
                for r, w in enumerate(wg.workers)]
        outs = ray_tpu.get(refs, timeout=120)
        np.testing.assert_allclose(outs[0], ref_final, rtol=1e-6)
        np.testing.assert_array_equal(outs[0], outs[1])
    finally:
        wg.shutdown()


# ---------------------------------------------------------------------------
# unit tests: abort wakeups, stale-epoch ingress, EF residuals across reform
# ---------------------------------------------------------------------------


def _stub_worker():
    """Duck-typed core worker for direct Group construction: absorbs
    event recording, has no reachable peers (abort fan-out no-ops)."""
    return SimpleNamespace(
        head=SimpleNamespace(fire=lambda *a, **k: None),
        _peer=lambda owner: None,
        node_id=b"stub",
    )


def test_abort_frame_wakes_blocked_recv():
    """An abort frame must wake a thread blocked in a collective recv
    within the abort-detection interval, raising the typed error."""
    from ray_tpu.collective import CollectiveAbortError
    from ray_tpu.collective import collective as col

    name = "abort-wake-unit"
    g = col.Group(name, 2, 0, _stub_worker(), epoch=7)
    g.peers = {0: {"addr": "127.0.0.1", "port": 1},
               1: {"addr": "127.0.0.1", "port": 2}}
    col._groups[name] = g
    try:
        got = []

        def waiter():
            t0 = time.monotonic()
            try:
                g._recv_obj(1, 1, "t", timeout=30.0, op="unit-op")
            except CollectiveAbortError as e:
                got.append((e, time.monotonic() - t0))

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        time.sleep(0.3)
        asyncio.run(col._rpc_coll_abort(None, {
            "group": name, "epoch": 7, "origin": 1,
            "reason": "unit kill", "op": "unit-op",
            "abort_id": "unit-abort-1"}))
        th.join(timeout=5)
        assert not th.is_alive(), "recv never woke on the abort frame"
        e, dt = got[0]
        assert dt < 3.0  # woke via cond notify, not the 30s timeout
        assert e.group == name and e.rank == 0 and e.epoch == 7
        assert e.origin_rank == 1 and e.op == "unit-op"
        assert "unit kill" in str(e)
        # abort is sticky: entering a new op on the incarnation raises
        with pytest.raises(CollectiveAbortError):
            g._poll_abort(op="next-op")
    finally:
        col._groups.pop(name, None)


def test_stale_abort_frame_ignored():
    """An abort frame from an older epoch must not poison a reformed
    incarnation."""
    from ray_tpu.collective import collective as col

    name = "stale-abort-unit"
    g = col.Group(name, 2, 0, _stub_worker(), epoch=5)
    col._groups[name] = g
    try:
        asyncio.run(col._rpc_coll_abort(None, {
            "group": name, "epoch": 4, "origin": 1, "reason": "old",
            "abort_id": "unit-abort-stale"}))
        assert g._abort is None
    finally:
        col._groups.pop(name, None)


def test_stale_epoch_frames_rejected_at_ingress():
    """Frames below the group's minimum live epoch are dropped at
    ingress — a reformed group can never consume the old incarnation's
    in-flight chunks."""
    from ray_tpu.collective import collective as col

    name = "stale-frames-unit"
    col._min_epochs[name] = 3
    try:
        ok = asyncio.run(col._rpc_coll_msg(None, {
            "group": name, "inc": 2, "seq": 1, "src": 0, "tag": "t",
            "payload": b"old"}))
        assert ok is False
        assert (name, 2, 1, 0, "t") not in col._mailbox().msgs
        ok = asyncio.run(col._rpc_coll_msg(None, {
            "group": name, "inc": 3, "seq": 1, "src": 0, "tag": "t",
            "payload": b"new"}))
        assert ok is True
        assert col._mailbox().msgs.pop((name, 3, 1, 0, "t")) == b"new"
    finally:
        col._min_epochs.pop(name, None)


class _Net:
    """Shared mailbox for threaded fake ranks (trimmed copy of the
    test_collective_ring harness — wire-serializes every frame)."""

    def __init__(self):
        self.cond = threading.Condition()
        self.msgs = {}

    def put(self, key, val):
        with self.cond:
            self.msgs[key] = val
            self.cond.notify_all()

    def take(self, key, timeout):
        deadline = time.monotonic() + timeout
        with self.cond:
            while key not in self.msgs:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise TimeoutError(key)
                self.cond.wait(min(rem, 0.2))
            return self.msgs.pop(key)


class _FakeGroup:
    def __init__(self, net, name, world, rank):
        self.net = net
        self.name = name
        self.world_size = world
        self.rank = rank
        self.seq = 0

    def _next_seq(self):
        self.seq += 1
        return self.seq

    def _send_obj(self, dst, seq, tag, obj, fire=False):
        from ray_tpu._private import serialization

        self.net.put((dst, self.name, seq, self.rank, tag),
                     serialization.pack_payload(obj))

    def _recv_obj(self, src, seq, tag, timeout=None, op=None):
        from ray_tpu._private import serialization

        msg = self.net.take((self.rank, self.name, seq, src, tag),
                            timeout or 30)
        return serialization.unpack_payload(msg)


def _run_world(world, fn, name):
    net = _Net()
    outs = [None] * world
    errs = []

    def go(r):
        try:
            outs[r] = fn(_FakeGroup(net, name, world, r), r)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=go, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    if errs:
        raise errs[0]
    return outs


def test_reform_drops_ef_residuals_numerics():
    """Membership change invalidates EF segment geometry: the residuals
    of the old incarnation are purged on reform, so post-reform int8
    numerics at the new world size are bit-identical to a fresh group's
    (no stale residual folds in)."""
    from ray_tpu.collective import ring

    data = {r: np.random.default_rng(50 + r).standard_normal(512)
            .astype(np.float32) for r in range(3)}

    def round_w3(g, r):
        return ring.ring_allreduce(g, data[r], codec="int8",
                                   ef_tag="w", timeout=30)

    _run_world(3, round_w3, "ef-reform")
    # lossy codec + EF tag ⇒ residuals were stored for this group
    assert any(k[0] == "ef-reform" for k in ring._ef_store), \
        "precondition: EF residuals should exist after an int8 round"

    # reform purges them (destroy_collective_group → ring.purge_group)
    ring.purge_group("ef-reform")
    assert not any(k[0] == "ef-reform" for k in ring._ef_store)

    def round_w2(g, r):
        return ring.ring_allreduce(g, data[r], codec="int8",
                                   ef_tag="w", timeout=30)

    reformed = _run_world(2, round_w2, "ef-reform")
    fresh = _run_world(2, round_w2, "ef-fresh-ref")
    for a, b in zip(reformed, fresh):
        np.testing.assert_array_equal(a, b)
    ring.purge_group("ef-reform")
    ring.purge_group("ef-fresh-ref")
