"""Distributed GC (reference counting) + lineage reconstruction tests.

Reference analogs: python/ray/tests/test_reference_counting.py and
test_object_reconstruction.py, scaled to the centralized-directory GC.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

MB = 1024 * 1024


@pytest.fixture
def cluster():
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30},
                store_capacity=64 * MB)
    c.connect()
    yield c
    c.shutdown()


@pytest.fixture
def cluster2():
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _store_used(cluster) -> int:
    return cluster.head_agent.store.used_bytes()


def test_dropping_refs_frees_store_memory(cluster):
    """Put 2x the 64 MiB store capacity in 8 MiB objects, dropping each ref:
    GC must free the pinned primaries or later puts fail."""
    base = _store_used(cluster)
    for i in range(16):
        ref = ray_tpu.put(np.full(MB, i, dtype=np.float64))  # 8 MiB each
        del ref
        gc.collect()
    # all refs dropped -> store returns to (near) baseline
    deadline = time.time() + 20
    while time.time() < deadline:
        if _store_used(cluster) <= base + 9 * MB:
            break
        time.sleep(0.2)
    assert _store_used(cluster) <= base + 9 * MB


def test_live_ref_protects_object(cluster):
    keep = ray_tpu.put(np.ones(MB, dtype=np.float64))
    for i in range(16):
        ref = ray_tpu.put(np.full(MB, i, dtype=np.float64))
        del ref
    gc.collect()
    time.sleep(1.0)
    # the kept object survived the churn (pinned + referenced)
    out = ray_tpu.get(keep, timeout=30)
    np.testing.assert_allclose(out, np.ones(MB))


def test_task_arg_pinned_until_completion(cluster):
    """Dropping the driver's ref while a task still uses it must not free
    the object under the task (submitted-task reference)."""

    @ray_tpu.remote(num_cpus=1)
    def slow_sum(arr):
        import time as _t

        _t.sleep(2.0)
        return float(arr.sum())

    ref = ray_tpu.put(np.ones(2 * MB, dtype=np.float64))
    out = slow_sum.remote(ref)
    del ref
    gc.collect()
    assert ray_tpu.get(out, timeout=60) == float(2 * MB)


def test_borrower_actor_keeps_object_alive(cluster):
    @ray_tpu.remote(num_cpus=0)
    class Holder:
        def __init__(self):
            self.ref = None

        def hold(self, refs):
            self.ref = refs[0]  # borrower keeps a live ObjectRef
            return True

        def read(self):
            return float(ray_tpu.get(self.ref, timeout=30).sum())

    h = Holder.remote()
    ref = ray_tpu.put(np.ones(MB, dtype=np.float64))
    # nested in a list so it arrives as an ObjectRef, not a resolved value
    assert ray_tpu.get(h.hold.remote([ref]), timeout=60)
    del ref
    gc.collect()
    time.sleep(1.5)  # GC would have fired if the borrow weren't counted
    assert ray_tpu.get(h.read.remote(), timeout=60) == float(MB)
    ray_tpu.kill(h)


def test_lineage_reconstruction_after_node_death(cluster2):
    """Kill the only node holding a task's (plasma) result; get() must
    transparently recompute it via the producing task."""
    victim = cluster2.agents[-1]
    pin = {"node_id": victim.node_id}

    @ray_tpu.remote(num_cpus=1, max_retries=3)
    def produce():
        return np.arange(500_000, dtype=np.float64)  # 4 MB -> plasma

    ref = produce.options(scheduling_strategy=pin).remote()
    first = ray_tpu.get(ref, timeout=60)
    expected = float(np.arange(500_000, dtype=np.float64).sum())
    assert float(first.sum()) == expected
    del first

    cluster2.remove_node(victim)
    time.sleep(0.5)
    # the only copy died with the node; reconstruction must recompute
    again = ray_tpu.get(ref, timeout=90)
    assert float(again.sum()) == expected


def test_lineage_chain_reconstruction(cluster2):
    """A downstream task whose dependency is lost triggers dep_lost ->
    owner reconstructs the dep -> the task runs."""
    victim = cluster2.agents[-1]
    pin = {"node_id": victim.node_id}

    @ray_tpu.remote(num_cpus=1, max_retries=3)
    def produce():
        return np.arange(500_000, dtype=np.float64)

    @ray_tpu.remote(num_cpus=1, max_retries=3)
    def consume(arr):
        return float(arr.sum())

    dep = produce.options(scheduling_strategy=pin).remote()
    ray_tpu.wait([dep], timeout=60)  # materialized on the victim
    cluster2.remove_node(victim)
    time.sleep(0.5)
    out = consume.remote(dep)
    expected = float(np.arange(500_000, dtype=np.float64).sum())
    assert ray_tpu.get(out, timeout=120) == expected


def test_spill_and_restore_2x_capacity(cluster):
    """VERDICT round-1 item 9 'done' bar: put 2x store capacity while
    KEEPING every ref (no GC eligible); primaries spill to disk and every
    object reads back intact."""
    refs = []
    for i in range(16):  # 16 x 8 MiB = 128 MiB in a 64 MiB store
        refs.append(ray_tpu.put(np.full(MB, i, dtype=np.float64)))
    agent = cluster.head_agent
    deadline = time.time() + 30
    while time.time() < deadline and not agent.spilled_files:
        time.sleep(0.2)
    assert agent.spilled_files, "store pressure never triggered spilling"
    # every object restores, including spilled ones
    for i, r in enumerate(refs):
        out = ray_tpu.get(r, timeout=60)
        assert out[0] == float(i) and out[-1] == float(i)


def test_spilled_object_freed_on_gc(cluster):
    """Dropping refs to a spilled object removes its spill file."""
    refs = [ray_tpu.put(np.full(MB, i, dtype=np.float64))
            for i in range(16)]
    agent = cluster.head_agent
    deadline = time.time() + 30
    while time.time() < deadline and not agent.spilled_files:
        time.sleep(0.2)
    assert agent.spilled_files
    import os

    paths = list(agent.spilled_files.values())
    del refs
    gc.collect()
    deadline = time.time() + 20
    while time.time() < deadline and any(os.path.exists(p) for p in paths):
        time.sleep(0.2)
    assert not any(os.path.exists(p) for p in paths)
