"""Cross-process trace propagation (reference
python/ray/util/tracing/tracing_helper.py:33): a nested submit chain
joins into one trace; user spans nest under their task."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _events_by_name(names, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        evs = {e["name"]: e for e in ray_tpu.list_tasks(limit=1000)}
        if all(n in evs for n in names):
            return evs
        time.sleep(0.25)
    raise AssertionError(f"events {names} never all arrived: "
                         f"{sorted(evs)}")


def test_nested_chain_joins_one_trace(cluster):
    from ray_tpu._private import trace as _trace
    from ray_tpu.util.profiling import profile

    @ray_tpu.remote
    def leaf_c():
        tid, span = _trace.current()
        return {"c_span": span, "trace_id": tid}

    @ray_tpu.remote
    def mid_b():
        with profile("inner_span"):
            out = ray_tpu.get(leaf_c.remote(), timeout=60)
        tid, span = _trace.current()
        out["b_span"] = span
        assert out["trace_id"] == tid  # child continued OUR trace
        return out

    @ray_tpu.remote
    def root_a():
        out = ray_tpu.get(mid_b.remote(), timeout=60)
        tid, span = _trace.current()
        out["a_span"] = span
        assert out["trace_id"] == tid
        return out

    out = ray_tpu.get(root_a.remote(), timeout=120)
    evs = _events_by_name(["root_a", "mid_b", "leaf_c", "inner_span"])

    # one trace id across all three tasks and the user span
    for name in ("root_a", "mid_b", "leaf_c", "inner_span"):
        assert evs[name]["trace"]["trace_id"] == out["trace_id"], name
    # parent chain: driver-rooted a -> b -> c
    assert "parent" not in evs["root_a"]["trace"]
    assert evs["mid_b"]["trace"]["parent"] == out["a_span"]
    assert evs["leaf_c"]["trace"]["parent"] == out["b_span"]
    # the user span nests under the task that opened it
    assert evs["inner_span"]["trace"]["parent"] == out["b_span"]
    # and the span ids ARE the task ids (joinable against task events)
    assert evs["mid_b"]["task_id"].hex() == out["b_span"]


def test_timeline_renders_flow_arrows(cluster):
    trace = ray_tpu.timeline()
    flows = [t for t in trace if t.get("cat") == "trace"]
    starts = [t for t in flows if t["ph"] == "s"]
    ends = [t for t in flows if t["ph"] == "f"]
    # the chain above yields at least two parent->child joins
    assert len(starts) >= 2 and len(ends) >= 2
    assert {t["id"] for t in starts} == {t["id"] for t in ends}
    # user spans carry their parent span in args
    spans = [t for t in trace if t.get("cat") == "user_span"]
    assert any(t["args"].get("parent_span") for t in spans)


def _span_events(kind, name_prefix, n=1, timeout=30, match=None):
    """Wait for >= n flight-recorder SPAN events of `kind` whose name
    starts with `name_prefix` (driver-side pending spans are flushed on
    every poll; worker-side ones ride their 0.5s flushers)."""
    from ray_tpu._private import flight_recorder

    evs = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        flight_recorder.flush_now()
        evs = [e for e in ray_tpu.list_tasks(limit=5000)
               if e.get("state") == "SPAN" and e.get("kind") == kind
               and e.get("name", "").startswith(name_prefix)
               and (match is None or match(e))]
        if len(evs) >= n:
            return evs
        time.sleep(0.25)
    raise AssertionError(
        f"only {len(evs)}/{n} {kind}:{name_prefix} spans arrived")


def test_serve_stream_spans_share_one_trace(cluster):
    """Satellite (d): one trace id covers submit -> prefill worker ->
    decode replica -> stream poll. The prompt length crosses
    prefill_threshold so the request traverses the DISAGGREGATED path:
    admission wait + prefill + KV handoff + first token + poll spans
    all carry the stream's trace."""
    import numpy as np

    from ray_tpu.serve.llm_pool import LLMPool

    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=4, prompt_buckets=(8, 16),
                   min_replicas=1, max_replicas=1, prefill_workers=1,
                   prefill_threshold=12, autoscale=False)
    try:
        prompt = np.random.RandomState(11).randint(
            1, 256, size=14).tolist()
        sub = pool.submit_stream({"prompt_ids": prompt, "max_tokens": 8})
        rid = sub["rid"]
        tid = pool._streams[rid]["trace"][0]
        deadline = time.time() + 120
        toks = []
        while time.time() < deadline:
            out = pool.poll_stream(rid)
            toks.extend(out["tokens"])
            if out["done"]:
                break
            time.sleep(0.02)
        assert len(toks) == 8

        segments = ["serve.admission_wait", "serve.prefill",
                    "serve.kv_handoff", "serve.first_token",
                    "serve.stream_poll"]
        for name in segments:
            evs = _span_events("serve", name, n=1, match=lambda e: (
                (e.get("trace") or {}).get("trace_id") == tid))
            assert evs, name
        # the prefill span reports the KV payload it shipped
        pf = _span_events("serve", "serve.prefill")[0]
        assert pf["attrs"]["kv_bytes"] > 0
        assert pf["attrs"]["prompt_tokens"] == len(prompt)
    finally:
        pool.shutdown()


def test_ring_collective_op_records_breakdown_span(cluster):
    """Satellite (d): a ring allreduce submitted under one driver trace
    leaves per-rank `collective` spans carrying that trace id and the
    rendezvous / chunk-wait / send / compute breakdown."""
    import numpy as np

    from ray_tpu._private import trace as _trace

    @ray_tpu.remote(num_cpus=0)
    class Rank:
        def init(self, world, rank, name):
            from ray_tpu.collective import init_collective_group

            init_collective_group(world, rank, group_name=name)
            self.group = name

        def ar(self):
            from ray_tpu._private import flight_recorder
            from ray_tpu._private import trace as tr
            from ray_tpu.collective import collective as col

            col.allreduce(np.ones(4096, np.float32), self.group,
                          timeout=60.0)
            flight_recorder.flush_now()
            return tr.current()[0]

    ranks = [Rank.remote() for _ in range(2)]
    group = "trace-ring"
    ray_tpu.get([a.init.remote(2, r, group)
                 for r, a in enumerate(ranks)], timeout=120)
    with _trace.root_scope() as (tid, _span):
        tids = ray_tpu.get([a.ar.remote() for a in ranks], timeout=120)
    assert set(tids) == {tid}  # both ranks executed inside OUR trace

    evs = _span_events("collective", "collective.", n=2, match=lambda e: (
        e["attrs"].get("group") == group))
    assert {e["attrs"]["rank"] for e in evs} == {0, 1}
    for e in evs:
        assert (e.get("trace") or {}).get("trace_id") == tid
        a = e["attrs"]
        assert a["world_size"] == 2 and a["chunks"] >= 2
        assert a["bytes_sent"] > 0 and a["bytes_recv"] > 0
        for k in ("rendezvous_s", "chunk_wait_s", "send_s", "compute_s"):
            assert a[k] >= 0.0, (k, a)
        # the breakdown never exceeds the span it decomposes
        dur = e["end_s"] - e["start_s"]
        assert a["chunk_wait_s"] + a["send_s"] + a["compute_s"] <= \
            dur + 0.05
    for a in ranks:
        ray_tpu.kill(a)


def test_actor_calls_carry_trace(cluster):
    from ray_tpu._private import trace as _trace

    @ray_tpu.remote
    class Svc:
        def span(self):
            cur = _trace.current()
            return cur

    svc = Svc.remote()
    cur = ray_tpu.get(svc.span.remote(), timeout=60)
    assert cur is not None  # actor call entered a trace scope
    tid, span = cur
    assert len(tid) == 16 and len(span) == 32
