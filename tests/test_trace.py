"""Cross-process trace propagation (reference
python/ray/util/tracing/tracing_helper.py:33): a nested submit chain
joins into one trace; user spans nest under their task."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _events_by_name(names, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        evs = {e["name"]: e for e in ray_tpu.list_tasks(limit=1000)}
        if all(n in evs for n in names):
            return evs
        time.sleep(0.25)
    raise AssertionError(f"events {names} never all arrived: "
                         f"{sorted(evs)}")


def test_nested_chain_joins_one_trace(cluster):
    from ray_tpu._private import trace as _trace
    from ray_tpu.util.profiling import profile

    @ray_tpu.remote
    def leaf_c():
        tid, span = _trace.current()
        return {"c_span": span, "trace_id": tid}

    @ray_tpu.remote
    def mid_b():
        with profile("inner_span"):
            out = ray_tpu.get(leaf_c.remote(), timeout=60)
        tid, span = _trace.current()
        out["b_span"] = span
        assert out["trace_id"] == tid  # child continued OUR trace
        return out

    @ray_tpu.remote
    def root_a():
        out = ray_tpu.get(mid_b.remote(), timeout=60)
        tid, span = _trace.current()
        out["a_span"] = span
        assert out["trace_id"] == tid
        return out

    out = ray_tpu.get(root_a.remote(), timeout=120)
    evs = _events_by_name(["root_a", "mid_b", "leaf_c", "inner_span"])

    # one trace id across all three tasks and the user span
    for name in ("root_a", "mid_b", "leaf_c", "inner_span"):
        assert evs[name]["trace"]["trace_id"] == out["trace_id"], name
    # parent chain: driver-rooted a -> b -> c
    assert "parent" not in evs["root_a"]["trace"]
    assert evs["mid_b"]["trace"]["parent"] == out["a_span"]
    assert evs["leaf_c"]["trace"]["parent"] == out["b_span"]
    # the user span nests under the task that opened it
    assert evs["inner_span"]["trace"]["parent"] == out["b_span"]
    # and the span ids ARE the task ids (joinable against task events)
    assert evs["mid_b"]["task_id"].hex() == out["b_span"]


def test_timeline_renders_flow_arrows(cluster):
    trace = ray_tpu.timeline()
    flows = [t for t in trace if t.get("cat") == "trace"]
    starts = [t for t in flows if t["ph"] == "s"]
    ends = [t for t in flows if t["ph"] == "f"]
    # the chain above yields at least two parent->child joins
    assert len(starts) >= 2 and len(ends) >= 2
    assert {t["id"] for t in starts} == {t["id"] for t in ends}
    # user spans carry their parent span in args
    spans = [t for t in trace if t.get("cat") == "user_span"]
    assert any(t["args"].get("parent_span") for t in spans)


def test_actor_calls_carry_trace(cluster):
    from ray_tpu._private import trace as _trace

    @ray_tpu.remote
    class Svc:
        def span(self):
            cur = _trace.current()
            return cur

    svc = Svc.remote()
    cur = ray_tpu.get(svc.span.remote(), timeout=60)
    assert cur is not None  # actor call entered a trace scope
    tid, span = cur
    assert len(tid) == 16 and len(span) == 32
