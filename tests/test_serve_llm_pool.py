"""Production serving tier (serve/llm_pool.py + models/kv_prefix_cache.py).

Covers the ISSUE-10 acceptance surface: multi-replica pool behind one
admission queue, prefill/decode disaggregation through the object
store, prefix/KV-cache reuse with BIT-IDENTICAL outputs vs cold
prefill, SLO-keyed replica demand (autoscaler hook), graceful replica
shutdown, chaos failover with no client-visible error, and token
streaming through pool + HTTP proxy chunked responses."""

import threading
import time

import numpy as np
import pytest

import jax

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster
from ray_tpu.models import llama
from ray_tpu.models.decode_engine import RaggedDecoder, prefill_kv
from ray_tpu.models.kv_prefix_cache import PrefixCache, chain_keys
from ray_tpu.serve.llm import LLMServer
from ray_tpu.serve.llm_pool import LLMPool

TINY = llama.LlamaConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=96, dtype="float32", remat=False)


def _greedy(params, prompt, max_new, max_len=96):
    return np.asarray(llama.greedy_generate(
        params, jax.numpy.asarray(np.asarray(prompt)[None]), TINY,
        max_new, max_len=max_len))[0, len(prompt):]


# ---------------- pure units (no cluster) ----------------

def test_serve_replica_demand_policy():
    from ray_tpu.autoscaler.demand_scheduler import serve_replica_demand

    kw = dict(min_replicas=1, max_replicas=8,
              target_queue_per_replica=4.0)
    # load-driven sizing
    assert serve_replica_demand(queue_depth=0, inflight=0,
                                n_replicas=1, **kw) == 1
    assert serve_replica_demand(queue_depth=10, inflight=6,
                                n_replicas=1, **kw) == 4
    # clamped to max
    assert serve_replica_demand(queue_depth=100, inflight=0,
                                n_replicas=2, **kw) == 8
    # SLO breach asks for one more than current even at low load
    assert serve_replica_demand(
        queue_depth=0, inflight=2, n_replicas=3, ttft_p99_s=1.0,
        target_ttft_s=0.5, **kw) == 4
    # scale-down held while ttft sits inside the headroom band
    assert serve_replica_demand(
        queue_depth=0, inflight=1, n_replicas=3, ttft_p99_s=0.4,
        target_ttft_s=0.5, **kw) == 3
    # scale-down allowed with real SLO headroom
    assert serve_replica_demand(
        queue_depth=0, inflight=1, n_replicas=3, ttft_p99_s=0.1,
        target_ttft_s=0.5, **kw) == 1


def test_replica_resource_demands_feed_bin_packer():
    from ray_tpu.autoscaler.demand_scheduler import (
        get_nodes_to_launch,
        replica_resource_demands,
    )

    demands = replica_resource_demands(3, {"TPU": 1.0})
    assert demands == [{"TPU": 1.0}] * 3
    launch = get_nodes_to_launch(
        demands,
        {"tpu": {"resources": {"TPU": 1.0, "CPU": 4.0},
                 "max_workers": 10}},
        free_capacities=[])
    assert launch == {"tpu": 3}


def test_prefix_cache_lru_and_match():
    pc = PrefixCache(block=4, max_bytes=10_000)
    toks = np.arange(1, 17, dtype=np.int32)  # 16 tokens, 4 blocks
    k = np.zeros((2, 12, 2, 8), np.float32)  # rows for 3 blocks
    v = np.ones_like(k)
    assert pc.insert(toks, k, v) == 3  # only 3 blocks have rows
    # deepest cached depth wins; capped at len(prompt)-1
    n, e = pc.match(toks[:13])
    assert n == 12 and e is not None
    n, e = pc.match(toks[:9])
    assert n == 8 and e is not None
    # diverging block breaks the chain
    other = toks.copy()
    other[5] = 99
    n, _ = pc.match(other)
    assert n == 4
    # byte-budget eviction is LRU
    small = PrefixCache(block=4, max_bytes=k[:, :4].nbytes * 2 + 1)
    small.insert(toks[:5], k[:, :4], v[:, :4])
    assert small.stats()["entries"] == 1
    small.insert(np.asarray([7, 7, 7, 7, 7], np.int32),
                 k[:, :4] + 1, v[:, :4])
    small.insert(np.asarray([9, 9, 9, 9, 9], np.int32),
                 k[:, :4] + 2, v[:, :4])
    st = small.stats()
    assert st["evictions"] >= 1 and st["bytes"] <= small.max_bytes
    assert chain_keys(toks, 4)[0] == chain_keys(toks[:7], 4)[0]


# ---------------- engine-level numerics (no cluster) ----------------

def test_prefix_cache_decode_bit_identical_to_cold_prefill():
    """THE prefix-cache acceptance numerics: a repeated-system-prompt
    workload must serve cached-prefix requests with tokens bit-identical
    to a cold full prefill."""
    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    pc = PrefixCache(block=8, max_bytes=1 << 26)
    eng = RaggedDecoder(params, TINY, slots=2, max_len=64,
                        chunk_tokens=3, prompt_buckets=(8, 16, 32),
                        prefix_cache=pc)
    rng = np.random.RandomState(1)
    head = rng.randint(1, 256, size=16).astype(np.int32)  # system prompt
    tails = [rng.randint(1, 256, size=n).astype(np.int32)
             for n in (4, 6, 3, 7)]
    prompts = [np.concatenate([head, t]) for t in tails]
    # first prompt populates the cache (cold); the rest hit it
    for i, p in enumerate(prompts):
        sid = eng.submit(p, 10)
        eng.drain()
        got = np.asarray(eng.pop_finished(sid).tokens[:10])
        np.testing.assert_array_equal(got, _greedy(params, p, 10, 64))
    st = pc.stats()
    assert st["hits"] >= len(prompts) - 1, st
    assert st["hit_rate"] > 0.5


def test_disaggregated_prefill_adopt_bit_identical():
    """prefill_kv on a 'prefill worker' + submit_prefilled adoption on
    a 'decode replica' must reproduce inline-prefill decode exactly."""
    import jax.numpy as jnp

    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, 256, size=20).astype(np.int32)
    padded = np.zeros((1, 32), np.int32)
    padded[0, :len(prompt)] = prompt
    k, v, toks0 = prefill_kv(params, jnp.asarray(padded),
                             jnp.asarray([len(prompt)], jnp.int32),
                             TINY, 64)
    kv = {"k": np.asarray(k[:, 0]), "v": np.asarray(v[:, 0]),
          "first_token": int(toks0[0]), "true_len": len(prompt)}
    eng = RaggedDecoder(params, TINY, slots=2, max_len=64,
                        chunk_tokens=3, prompt_buckets=(8, 16, 32))
    sid = eng.submit_prefilled(prompt, 10, kv)
    eng.drain()
    got = np.asarray(eng.pop_finished(sid).tokens[:10])
    np.testing.assert_array_equal(got, _greedy(params, prompt, 10, 64))
    # wrong-shape KV is rejected at submit, not inside the pump
    with pytest.raises(ValueError):
        eng.submit_prefilled(prompt, 10, {**kv, "k": kv["k"][:, :32]})


def test_engine_stats_and_streaming_take():
    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    eng = RaggedDecoder(params, TINY, slots=2, max_len=64,
                        chunk_tokens=4, prompt_buckets=(8,))
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, 256, size=6).astype(np.int32)
    sid = eng.submit(prompt, 9)
    st = eng.stats()
    assert st["slots"] == 2 and st["queued"] == 1 and st["active"] == 0
    got, done = [], False
    while not done:
        eng.pump()
        new, done = eng.take_tokens(sid)
        got.extend(new)
    np.testing.assert_array_equal(np.asarray(got[:9]),
                                  _greedy(params, prompt, 9, 64))
    st = eng.stats()
    assert st["total_tokens"] >= 9
    assert "tokens_per_sec" in st and "utilization" in st
    # fully-taken finished stream is purged
    assert eng.take_tokens(sid) == ([], True)


def test_deployment_serving_options_fold_into_autoscaling():
    from ray_tpu.serve.api import Deployment

    d = Deployment(LLMServer, min_replicas=2, max_replicas=5,
                   target_ttft_s=0.25)
    assert d.autoscaling_config == {
        "min_replicas": 2, "max_replicas": 5, "target_ttft_s": 0.25}
    # survives .options() round-trips
    d2 = d.options(num_replicas=3)
    assert d2.autoscaling_config == d.autoscaling_config


# ---------------- pool end-to-end (cluster) ----------------

@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    try:
        serve.shutdown()
    except Exception:  # noqa: BLE001
        pass
    c.shutdown()


def _drain_stream(pool, rid, deadline_s=120.0):
    toks = []
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        out = pool.poll_stream(rid)
        toks.extend(out["tokens"])
        if out["done"]:
            return toks
        time.sleep(0.01)
    raise TimeoutError("stream did not finish")


def test_pool_generate_stream_and_disagg_parity(cluster):
    """2 decode replicas + 1 prefill worker + prefix cache, one shared
    weight publish: short prompts (inline prefill), long prompts
    (disaggregated through the object store), and streaming all return
    the exact greedy continuation."""
    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=4, prompt_buckets=(8, 16),
                   min_replicas=2, max_replicas=2, prefill_workers=1,
                   prefill_threshold=12, prefix_cache_block=4,
                   autoscale=False)
    try:
        params = llama.init_params(TINY, jax.random.PRNGKey(0))
        rng = np.random.RandomState(3)
        for n in (7, 14):  # inline vs disaggregated prefill
            p = rng.randint(1, 256, size=n).astype(np.int32)
            out = pool.generate(p.tolist(), 8)
            np.testing.assert_array_equal(
                np.asarray(out["tokens"]), _greedy(params, p, 8))
        # streaming: short (inline) AND long (disaggregated — the KV
        # ref rides submit_stream_prefilled as a top-level arg)
        for n in (7, 14):
            p = rng.randint(1, 256, size=n).astype(np.int32)
            rid = pool.submit_stream(
                {"prompt_ids": p.tolist(), "max_tokens": 8})["rid"]
            np.testing.assert_array_equal(
                np.asarray(_drain_stream(pool, rid)),
                _greedy(params, p, 8))
        st = pool.stats()
        assert st["replicas"] == 2
        assert st["ttft_p99_s"] is not None
        assert set(st["per_replica"]) == {"decode-1", "decode-2"}
    finally:
        pool.shutdown()


def test_pool_consumer_tags_ride_fetch_path(cluster):
    """The pool's two big transfers declare their consumer identity:
    weight broadcast submits with {owner: weights, qos: bulk} and the
    executor's param fetch carries those tags to fetch_object; the
    prefill→decode KV handoff submits with {owner: kv-handoff, qos: kv}.
    (Cross-node, these tags select the pull's pacer class and owner
    attribution — test_data_plane asserts that half.)"""
    w = cluster._driver
    submits = []
    orig_submit = w.submit_actor_task

    def rec_submit(*a, **k):
        if k.get("fetch_tags"):
            submits.append(dict(k["fetch_tags"]))
        return orig_submit(*a, **k)

    w.submit_actor_task = rec_submit
    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=4, prompt_buckets=(8, 16),
                   min_replicas=2, max_replicas=2, prefill_workers=1,
                   prefill_threshold=12, autoscale=False)
    try:
        rng = np.random.RandomState(5)
        p = rng.randint(1, 256, size=14).astype(np.int32)  # disagg path
        pool.generate(p.tolist(), 4)
        params = llama.init_params(TINY, jax.random.PRNGKey(1))
        v = pool.publish_weights(params)
        assert pool.wait_version(v, timeout=60)
        assert {"qos": "bulk", "owner": "weights"} in submits, submits
        assert {"qos": "kv", "owner": "kv-handoff"} in submits, submits
    finally:
        w.submit_actor_task = orig_submit
        pool.shutdown()


def test_pool_chaos_replica_kill_no_client_visible_error(cluster):
    """THE chaos acceptance: kill a decode replica mid-stream and
    mid-generate; the pool re-queues in-flight work to survivors and
    clients see exact tokens, never an error."""
    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=2, prompt_buckets=(8,),
                   min_replicas=3, max_replicas=3, autoscale=False,
                   chunk_delay_s=0.02)
    try:
        params = llama.init_params(TINY, jax.random.PRNGKey(0))
        rng = np.random.RandomState(5)
        p = rng.randint(1, 256, size=6).astype(np.int32)
        rid = pool.submit_stream(
            {"prompt_ids": p.tolist(), "max_tokens": 40})["rid"]
        toks = []
        while len(toks) < 6:
            out = pool.poll_stream(rid)
            toks.extend(out["tokens"])
            assert not out["done"]
            time.sleep(0.01)
        victim = pool._streams[rid]["rep"]
        ray_tpu.kill(victim.handle)  # mid-stream kill
        t0 = time.time()
        while time.time() - t0 < 120:
            out = pool.poll_stream(rid)
            toks.extend(out["tokens"])
            if out["done"]:
                break
            time.sleep(0.01)
        np.testing.assert_array_equal(np.asarray(toks),
                                      _greedy(params, p, 40))

        # blocking path: kill one of the survivors with calls in flight
        outs = [None] * 4
        prompts = [rng.randint(1, 256, size=6).astype(np.int32)
                   for _ in range(4)]

        def one(i):
            outs[i] = pool.generate(prompts[i].tolist(), 30)

        ths = [threading.Thread(target=one, args=(i,)) for i in range(4)]
        for t in ths:
            t.start()
        time.sleep(0.3)
        ray_tpu.kill(pool._alive()[0].handle)
        for t in ths:
            t.join(120)
        for pp, out in zip(prompts, outs):
            assert out is not None, "client saw an error"
            np.testing.assert_array_equal(
                np.asarray(out["tokens"]), _greedy(params, pp, 30))
    finally:
        pool.shutdown()


def _sampled_ref(params, prompt, n, *, temperature, seed):
    """Reference sampled decode on a plain (non-speculative) engine —
    the sequence any replica must reproduce for this (prompt, seed)."""
    eng = RaggedDecoder(params, TINY, slots=2, max_len=96,
                        chunk_tokens=4, prompt_buckets=(8,))
    sid = eng.submit(np.asarray(prompt, np.int32), n,
                     temperature=temperature, seed=seed)
    eng.drain()
    return np.asarray(eng.pop_finished(sid).tokens[:n])


@pytest.mark.slow  # heaviest failover soak; replica-kill failover stays
# tier-1 via test_pool_chaos_replica_kill_no_client_visible_error and
# the spec-on greedy path via the decode-spec unit tests
def test_pool_replica_kill_failover_spec_sampled_exact(cluster):
    """ISSUE-19 acceptance: kill a decode replica mid-stream with
    speculative decoding ON and sampling ON; the re-queued stream must
    reproduce the EXACT token sequence of a plain non-speculative
    engine — acceptance is judged against the target's own
    (seed, position) RNG-lane token, so seed-replay is exact no matter
    how many draft tokens each pump accepted before or after the
    kill."""
    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=2, prompt_buckets=(8,),
                   min_replicas=2, max_replicas=2, autoscale=False,
                   chunk_delay_s=0.02,
                   spec_depth=4, spec_draft_layers=1)
    try:
        params = llama.init_params(TINY, jax.random.PRNGKey(0))
        rng = np.random.RandomState(7)
        p = rng.randint(1, 256, size=6).astype(np.int32)
        ref = _sampled_ref(params, p, 32, temperature=0.8, seed=12345)
        rid = pool.submit_stream(
            {"prompt_ids": p.tolist(), "max_tokens": 32,
             "temperature": 0.8, "seed": 12345})["rid"]
        toks = []
        while len(toks) < 6:
            out = pool.poll_stream(rid)
            toks.extend(out["tokens"])
            assert not out["done"]
            time.sleep(0.01)
        ray_tpu.kill(pool._streams[rid]["rep"].handle)  # mid-stream
        t0 = time.time()
        while time.time() - t0 < 120:
            out = pool.poll_stream(rid)
            toks.extend(out["tokens"])
            if out["done"]:
                break
            time.sleep(0.01)
        np.testing.assert_array_equal(np.asarray(toks), ref)
        # speculation actually ran on the decoding replicas
        st = pool.stats()
        specs = [s.get("spec") for s in st["per_replica"].values()
                 if isinstance(s, dict)]
        assert any(sp and sp["pumps"] > 0 for sp in specs)
    finally:
        pool.shutdown()


def test_pool_multiplex_routes_by_model_id(cluster):
    """Model multiplexing (serve/multiplex.py wired to real weight
    swaps): requests routed by model_id decode under THAT model's
    weights — each compared against its own reference greedy decode —
    with the construction model addressable as "" and unregistered ids
    rejected.  The LRU keeps swapped-in models resident as object-store
    refs."""
    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=4, prompt_buckets=(8,),
                   min_replicas=1, max_replicas=1, autoscale=False)
    try:
        base = llama.init_params(TINY, jax.random.PRNGKey(0))
        alt = llama.init_params(TINY, jax.random.PRNGKey(42))
        pool.register_model("alt", alt)
        rng = np.random.RandomState(8)
        p = rng.randint(1, 256, size=6).astype(np.int32)
        a1 = pool.generate(p.tolist(), 12)
        np.testing.assert_array_equal(a1["tokens"],
                                      _greedy(base, p, 12))
        b = pool.generate(p.tolist(), 12, model_id="alt")
        np.testing.assert_array_equal(b["tokens"], _greedy(alt, p, 12))
        assert not np.array_equal(b["tokens"], a1["tokens"])
        # back to the construction model by its reserved id
        a2 = pool.generate(p.tolist(), 12, model_id="")
        np.testing.assert_array_equal(a2["tokens"], a1["tokens"])
        st = pool.stats()
        assert st["active_model"] == ""
        assert st["registered_models"] == ["alt"]
        assert "alt" in st["resident_models"]
        with pytest.raises(KeyError):
            pool.generate(p.tolist(), 4, model_id="nope")
    finally:
        pool.shutdown()


def test_pool_autoscales_up_and_drains_down(cluster):
    """Queue pressure scales the pool toward max_replicas via the
    demand hook; idleness drains it back to min (draining replicas get
    an explicit LLMServer.shutdown before the kill)."""
    pool = LLMPool(model_size="tiny", slots=1, max_len=96,
                   chunk_tokens=2, prompt_buckets=(8,),
                   min_replicas=1, max_replicas=2,
                   target_queue_per_replica=1.0, autoscale=True,
                   chunk_delay_s=0.05)
    pool.AUTOSCALE_PERIOD_S = 0.2
    try:
        rng = np.random.RandomState(7)
        prompts = [rng.randint(1, 256, size=6).astype(np.int32)
                   for _ in range(6)]
        ths = [threading.Thread(
            target=lambda p=p: pool.generate(p.tolist(), 30))
            for p in prompts]
        for t in ths:
            t.start()
        deadline = time.time() + 60
        while len(pool._alive()) < 2 and time.time() < deadline:
            time.sleep(0.1)
        assert len(pool._alive()) == 2, "pool never scaled up"
        for t in ths:
            t.join(120)
        deadline = time.time() + 90
        while time.time() < deadline:
            with pool._lock:
                live = [r for r in pool._replicas if not r.draining]
            if len(live) <= 1:
                break
            time.sleep(0.2)
        assert len([r for r in pool._replicas
                    if not r.draining]) <= 1, "pool never drained down"
    finally:
        pool.shutdown()


def test_llm_server_shutdown_drains_deterministically():
    """Satellite: explicit shutdown() replaces the nondeterministic
    __del__ teardown — in-flight streams finish, new admits are
    rejected, and the pump thread is joined."""
    srv = LLMServer(model_size="tiny", slots=2, max_len=96,
                    chunk_tokens=4, prompt_buckets=(8,))
    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    p = np.asarray([3, 5, 7, 9], np.int32)
    done = {}

    def gen():
        done["out"] = srv.generate(p.tolist(), 12)

    th = threading.Thread(target=gen)
    th.start()
    time.sleep(0.05)  # let it admit
    assert srv.shutdown(drain_s=60.0) is True
    th.join(30)
    np.testing.assert_array_equal(
        np.asarray(done["out"]["tokens"]), _greedy(params, p, 12))
    assert not srv._pump_thread.is_alive()
    with pytest.raises(RuntimeError):
        srv.generate(p.tolist(), 4)


def test_http_proxy_streams_chunked_tokens(cluster):
    """Streaming satellite: {"stream": true} through the HTTP proxy
    returns chunked NDJSON token batches that concatenate to the exact
    greedy continuation."""
    import http.client
    import json as _json

    from ray_tpu.serve.api import Deployment

    dep = Deployment(LLMServer, max_concurrent_queries=8,
                     resources={"CPU": 0}, route_prefix="/sllm")
    serve.run(dep, name="sllm", init_kwargs={
        "model_size": "tiny", "slots": 2, "max_len": 96,
        "chunk_tokens": 4, "prompt_buckets": (8,),
        "chunk_delay_s": 0.05})
    host, port = serve.start_http_proxy()
    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    p = np.asarray([2, 4, 6, 8, 10], np.int32)
    body = _json.dumps({"prompt_ids": p.tolist(), "max_tokens": 24,
                        "stream": True})
    deadline = time.time() + 120
    toks, chunks = [], 0
    while time.time() < deadline:
        conn = http.client.HTTPConnection(host, port, timeout=60)
        try:
            conn.request("POST", "/sllm", body,
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            if r.status != 200:  # route still propagating
                time.sleep(0.5)
                continue
            assert r.getheader("Transfer-Encoding") == "chunked"
            toks, chunks = [], 0
            for line in r:  # http.client de-chunks line by line
                if not line.strip():
                    continue
                msg = _json.loads(line)
                assert "error" not in msg, msg
                if msg.get("tokens"):
                    toks.extend(msg["tokens"])
                    chunks += 1
                if msg.get("done"):
                    break
            break
        finally:
            conn.close()
    np.testing.assert_array_equal(np.asarray(toks),
                                  _greedy(params, p, 24))
    assert chunks >= 2, "tokens arrived in one burst — not streamed"


def test_job_submission_log_tailing(cluster):
    """Streaming satellite (job side): tail_job_logs yields increments
    as the job prints, finishing when the job does."""
    import sys

    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    code = ("import time\n"
            "for i in range(5):\n"
            "    print('tok', i, flush=True)\n"
            "    time.sleep(0.2)\n")
    sid = client.submit_job(
        entrypoint=f'{sys.executable} -c "{code}"')
    chunks = list(client.tail_job_logs(sid, poll_s=0.1, timeout=120))
    text = "".join(chunks)
    assert [f"tok {i}" in text for i in range(5)] == [True] * 5
    assert len(chunks) >= 2, "logs arrived in one burst — not tailed"
    client.delete_job(sid)
