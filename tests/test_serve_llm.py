"""Continuous-batching LLM serving (serve/llm.py + decode_engine.py):
greedy-parity of the ragged engine under slot churn, and the Serve
deployment path end-to-end with concurrent requests sharing one slot
batch (reference anchor: OPT-30B inference release test)."""

import time

import numpy as np
import pytest

import jax

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster
from ray_tpu.models import llama
from ray_tpu.models.decode_engine import RaggedDecoder
from ray_tpu.serve.api import Deployment
from ray_tpu.serve.llm import LLMServer

TINY = llama.LlamaConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=128, dtype="float32", remat=False)


def test_ragged_engine_matches_greedy_generate():
    """Every stream decoded by the continuous-batching engine — under
    queueing, staggered admission, and slot reuse — must match the
    per-stream greedy_generate reference exactly."""
    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    prompts = [rng.randint(1, 256, size=n).astype(np.int32)
               for n in (5, 9, 17, 26, 31)]
    max_new = 10

    eng = RaggedDecoder(params, TINY, slots=2, max_len=64,
                        chunk_tokens=3, prompt_buckets=(8, 16, 32))
    sids = [eng.submit(p, max_new) for p in prompts]
    eng.drain()
    for sid, p in zip(sids, prompts):
        want = np.asarray(llama.greedy_generate(
            params, jax.numpy.asarray(p[None, :]), TINY, max_new,
            max_len=64))[0, len(p):]
        got = np.asarray(eng.pop_finished(sid).tokens[:max_new])
        np.testing.assert_array_equal(got, want)


def test_engine_interleaves_new_streams_into_free_slots():
    """Continuous batching proper: a LATER-submitted stream must start
    decoding before an earlier long stream finishes (static batching
    would serialize them)."""
    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.RandomState(2)
    eng = RaggedDecoder(params, TINY, slots=2, max_len=96,
                        chunk_tokens=4, prompt_buckets=(8,))
    long_sid = eng.submit(rng.randint(1, 256, 6).astype(np.int32), 40)
    short_sid = eng.submit(rng.randint(1, 256, 6).astype(np.int32), 4)
    eng.pump()  # both admitted (2 slots); short finishes first
    while short_sid not in eng.finished:
        eng.pump()
    assert long_sid not in eng.finished  # long still running
    late_sid = eng.submit(rng.randint(1, 256, 6).astype(np.int32), 4)
    eng.pump()  # late stream admitted into the freed slot
    got_service = (late_sid in eng.finished or any(
        s is not None and s.sid == late_sid for s in eng.slot_stream))
    assert got_service, "late stream not admitted while long one runs"
    assert long_sid not in eng.finished  # interleaved, not serialized
    eng.drain()
    assert late_sid in eng.finished and long_sid in eng.finished


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    serve.shutdown()
    c.shutdown()


def test_llm_deployment_concurrent_requests(cluster):
    """Concurrent generate() calls through a Serve replica share ONE
    slot batch; every request returns its exact greedy continuation."""
    dep = Deployment(LLMServer, max_concurrent_queries=8,
                     resources={"CPU": 0}, route_prefix="/llm")
    handle = serve.run(dep, name="llm", init_kwargs={
        "model_size": "tiny", "slots": 2, "max_len": 96,
        "chunk_tokens": 4, "prompt_buckets": (8, 16)})

    params = llama.init_params(TINY, jax.random.PRNGKey(0))
    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, 256, size=7).astype(np.int32)
               for _ in range(5)]
    max_new = 8
    t0 = time.perf_counter()
    refs = [handle.remote({"prompt_ids": p.tolist(),
                           "max_tokens": max_new}) for p in prompts]
    outs = ray_tpu.get(refs, timeout=300)
    assert time.perf_counter() - t0 < 300
    for p, out in zip(prompts, outs):
        want = np.asarray(llama.greedy_generate(
            params, jax.numpy.asarray(p[None, :]), TINY, max_new,
            max_len=96))[0, len(p):]
        np.testing.assert_array_equal(np.asarray(out["tokens"]), want)
        assert len(out["token_times_s"]) == max_new
        assert out["token_times_s"][0] >= out["submitted_s"]
