"""Multi-tenant QoS enforcement (_private/net_qos.py + the paths it
gates).

Covers the ISSUE-16 acceptance surface: strict-priority token-bucket
pacing per peer (kv > collective > bulk), chunk-granularity bulk
preemption with byte-identical resume through the agents' pull path,
the bounded bulk share (anti-starvation floor), typed-retryable
NetPaceError on deadline/injection (never a deadlock), pacer-state
purge on peer death and group teardown, per-tenant weighted fair
admission at the pool head, the per-replica batched stream-poll
surface, and link-aware replica placement off `net_tx_bytes_total`.
"""

import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import config as cfg
from ray_tpu._private import fault_injection
from ray_tpu._private import net_accounting as net
from ray_tpu._private import net_qos as qos
from ray_tpu.cluster_utils import Cluster


# ---------------- pure units (no cluster) ----------------

@pytest.fixture
def paced():
    """Finite-rate pacer: 0.8 mbps = 100 KB/s against a 100 KB window
    (1 s full refill — slow enough that priority/park assertions are
    race-free), bulk floor 20 KB per interval."""
    qos.reset()
    net.reset_local()
    cfg.set_system_config({"net_qos_rate_mbps": 0.8,
                           "net_qos_window_bytes": 100_000})
    yield qos
    cfg.set_system_config({"net_qos_rate_mbps": 0.0,
                           "net_qos_window_bytes": 0})
    fault_injection.clear()
    qos.reset()


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_unlimited_rate_is_grant_and_tally_only():
    qos.reset()
    assert not qos.enforced()
    for _ in range(3):
        assert qos.try_acquire("pz", "bulk", 10 * 2**20) == 0.0
    qos.acquire("pz", "kv", 2**20, timeout=0.1)  # returns immediately
    st = qos.stats("pz")
    assert st["granted_bytes"]["bulk"] == 30 * 2**20
    assert st["granted_bytes"]["kv"] == 2**20
    assert st["parks"] == {"kv": 0, "collective": 0, "bulk": 0}
    qos.reset()


def test_strict_priority_parks_lower_classes(paced):
    assert qos.try_acquire("p1", "bulk", 100_000) == 0.0  # drain window
    done = []
    t = threading.Thread(
        target=lambda: (qos.acquire("p1", "kv", 50_000, timeout=10),
                        done.append(True)))
    t.start()
    assert _wait_for(lambda: qos.stats("p1")["waiting"]["kv"] == 1)
    # collective parks while kv waits; bulk past its floor parks AND
    # counts as a preemption of the in-flight bulk transfer
    assert qos.try_acquire("p1", "collective", 1_000) > 0
    assert qos.try_acquire("p1", "bulk", 30_000) > 0
    st = qos.stats("p1")
    assert st["parks"]["collective"] >= 1
    assert st["parks"]["bulk"] >= 1
    assert st["preemptions"] >= 1
    t.join(timeout=10)
    assert done, "parked kv acquire never granted"
    # the refilled tokens went to kv first, not the parked lower classes
    st = qos.stats("p1")
    assert st["granted_bytes"]["kv"] == 50_000
    assert st["granted_bytes"]["collective"] == 0


def test_bulk_floor_progresses_under_kv_pressure(paced):
    cfg.set_system_config({"net_qos_window_bytes": 200_000})
    # drain via collective so the bulk floor accounting starts at zero
    assert qos.try_acquire("p2", "collective", 200_000) == 0.0
    t = threading.Thread(
        target=lambda: qos.acquire("p2", "kv", 150_000, timeout=15))
    t.start()
    assert _wait_for(lambda: qos.stats("p2")["waiting"]["kv"] == 1)
    time.sleep(0.4)  # ~40 KB refilled; kv (150 KB) still far short
    # bulk inside its 40 KB per-interval share progresses even while
    # kv waits (anti-starvation)...
    assert qos.try_acquire("p2", "bulk", 20_000) == 0.0
    # ...but the next grant would exceed the share: parked
    assert qos.try_acquire("p2", "bulk", 25_000) > 0
    t.join(timeout=15)
    assert qos.stats("p2")["granted_bytes"]["kv"] == 150_000


def test_acquire_deadline_raises_typed_retryable(paced):
    assert qos.try_acquire("p3", "bulk", 100_000) == 0.0
    t0 = time.monotonic()
    with pytest.raises(qos.NetPaceError) as ei:
        # larger than the window capacity: never grantable
        qos.acquire("p3", "kv", 1_000_000, timeout=0.3)
    assert 0.25 <= time.monotonic() - t0 < 3.0
    assert ei.value.retryable is True
    assert ei.value.peer == "p3" and ei.value.qos_class == "kv"
    # the dead waiter deregistered: no phantom priority block remains
    st = qos.stats("p3")
    assert st["waiting"] == {"kv": 0, "collective": 0, "bulk": 0}
    assert 0.0 < qos.try_acquire("p3", "bulk", 50_000) <= 2.0  # hint capped


def test_purge_resets_window_and_group_peers(paced):
    assert qos.try_acquire("p4", "bulk", 100_000) == 0.0
    assert qos.try_acquire("p4", "bulk", 100_000) > 0  # exhausted
    assert qos.purge_peer("p4") is True
    assert qos.stats("p4") == {}
    # a reused address starts from a fresh full bucket, not the
    # exhausted window of the dead peer
    assert qos.try_acquire("p4", "bulk", 100_000) == 0.0
    for peer in ("g9:r0", "g9:r1", "other"):
        qos.try_acquire(peer, "bulk", 1)
    assert qos.purge_group_peers("g9") == 2
    assert qos.stats("g9:r0") == {} and qos.stats("other")


def test_net_pace_drop_is_typed_retryable(paced):
    fault_injection.configure([
        {"site": "net.pace", "action": "drop", "count": 0}])
    with pytest.raises(qos.NetPaceError) as ei:
        qos.try_acquire("pf", "bulk", 10)
    assert ei.value.retryable is True
    with pytest.raises(qos.NetPaceError):
        qos.acquire("pf", "kv", 10, timeout=1.0)
    fault_injection.clear()
    assert qos.try_acquire("pf", "bulk", 10) == 0.0  # recovered


def test_net_pace_stall_never_deadlocks(paced):
    import asyncio

    fault_injection.configure([
        {"site": "net.pace", "action": "stall", "delay_s": 0.15,
         "count": 0}])
    try:
        # async-path callers are NOT slept on their loop: the injected
        # stall surfaces as a retry hint immediately
        t0 = time.perf_counter()
        hint = qos.try_acquire("ps", "bulk", 10)
        assert hint >= 0.01
        assert time.perf_counter() - t0 < 0.1
        # sync acquire absorbs the stall and still completes bounded
        t0 = time.perf_counter()
        qos.acquire("ps", "kv", 10_000, timeout=5.0)
        assert 0.1 <= time.perf_counter() - t0 < 5.0
        # a persistent stall converges to the typed error, not a hang
        async def go():
            await qos.acquire_async("ps", "bulk", 10, timeout=0.5)

        t0 = time.perf_counter()
        with pytest.raises(qos.NetPaceError):
            asyncio.run(go())
        assert time.perf_counter() - t0 < 3.0
    finally:
        fault_injection.clear()


def test_chaos_qos_profile():
    from ray_tpu._private import chaos

    p1 = chaos.gen_fault_plan(7, profile="qos", n_prefill=1)
    assert p1.env_value() == chaos.gen_fault_plan(
        7, profile="qos", n_prefill=1).env_value()
    sites = set()
    for seed in range(80):
        plan = chaos.gen_fault_plan(seed, profile="qos", n_prefill=1)
        for s in plan.specs:
            sites.add(s["site"])
            if s["action"] in ("delay", "stall"):
                assert s["delay_s"] > 0
            if s["site"] == "net.pace":
                assert s in plan.driver_specs
    assert "net.pace" in sites
    assert "net.pace" in chaos.DRIVER_SITES
    # the train profile stays byte-identical for replayable soak seeds:
    # qos sites must never leak into it
    for seed in range(40):
        for s in chaos.gen_fault_plan(seed, profile="train").specs:
            assert s["site"] != "net.pace"


def test_link_aware_placement_avoids_saturated_links():
    from ray_tpu.autoscaler.demand_scheduler import (get_nodes_to_launch,
                                                     link_tx_by_peer)

    rows = [
        {"name": "net_tx_bytes_total",
         "tags": [("peer", "aaaa1111"), ("qos_class", "collective"),
                  ("owner", "gang"), ("tenant", "-")], "value": 8e9},
        {"name": "net_tx_bytes_total",
         "tags": [("peer", "aaaa1111"), ("qos_class", "bulk"),
                  ("owner", "spill"), ("tenant", "-")], "value": 4e9},
        {"name": "net_tx_bytes_total",
         "tags": [("peer", "bbbb2222"), ("qos_class", "kv"),
                  ("owner", "serve"), ("tenant", "a")], "value": 1e6},
        {"name": "other_metric", "tags": [("peer", "aaaa1111")],
         "value": 1e18},
    ]
    load = link_tx_by_peer(rows)
    assert load == {"aaaa1111": 12e9, "bbbb2222": 1e6}

    free = [{"TPU": 1.0}, {"TPU": 1.0}]
    ids = ["aaaa1111", "bbbb2222"]
    nt = {"tpu": {"resources": {"TPU": 1.0}, "max_workers": 8}}
    kw = dict(free_node_ids=ids, link_tx_bytes_per_s=load,
              link_saturation_bytes_per_s=1e9)
    # one replica lands on the cold link, no launch
    assert get_nodes_to_launch([{"TPU": 1.0}], nt,
                               [dict(c) for c in free], **kw) == {}
    # a second replica avoids the gang-saturated node: fresh launch
    assert get_nodes_to_launch([{"TPU": 1.0}] * 2, nt,
                               [dict(c) for c in free],
                               **kw) == {"tpu": 1}
    # ...unless nothing can launch — then the saturated node still
    # beats not placing at all
    nt0 = {"tpu": {"resources": {"TPU": 1.0}, "max_workers": 0}}
    assert get_nodes_to_launch([{"TPU": 1.0}] * 2, nt0,
                               [dict(c) for c in free], **kw) == {}
    # without link signals behaviour is unchanged
    assert get_nodes_to_launch([{"TPU": 1.0}] * 2, nt,
                               [dict(c) for c in free]) == {}


# ---------------- agents-only integration (no driver) ----------------

@pytest.fixture
def agents_cluster():
    # agents only, NO driver connect: drives the agent-to-agent chunk
    # path directly (same idiom as test_flight_recorder)
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30},
                store_capacity=256 * 2**20)
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    yield c
    c.shutdown()


def _seed_owned(cluster, agent, data: bytes, owner_wid: bytes):
    oid = os.urandom(16)
    agent.store.put_bytes(oid, data, metadata=b"")
    cluster.io.run(agent.rpc_object_sealed(
        None, {"object_id": oid, "size": len(data),
               "owner": {"worker_id": owner_wid}}))
    return oid


def test_bulk_pull_preempted_by_kv_resumes_byte_identical(agents_cluster):
    """The tentpole end-to-end: a multi-chunk bulk pull on a paced link
    is preempted at chunk granularity by kv-class acquires on the same
    peer, parks (never cancels), and resumes byte-identically — with
    attribution still exact with the pacer ON."""
    c = agents_cluster
    src, dst = c.agents[0], c.agents[1]
    src_label = src.node_id.hex()[:8]
    old_chunk = cfg.get("object_transfer_chunk_bytes")
    qos.reset()
    net.reset_local()
    try:
        # 256 KB chunks over a 1 MB/s paced link with a one-chunk
        # window: each chunk needs a full refill, so kv pressure
        # deterministically parks the in-flight bulk transfer
        cfg.set_system_config({
            "object_transfer_chunk_bytes": 256 * 1024,
            "net_qos_rate_mbps": 8.0,
            "net_qos_window_bytes": 256 * 1024,
        })
        wid = bytes([0xAB]) * 16
        data = os.urandom(2 * 2**20)  # 8 chunks
        oid = _seed_owned(c, src, data, wid)

        pulled = []

        def pull():
            pulled.append(c.io.run(dst.rpc_fetch_object(
                None, {"object_id": oid, "timeout": 120})))

        pt = threading.Thread(target=pull)
        pt.start()
        time.sleep(0.2)  # the pull is mid-flight
        # hammer the pull-side peer window with latency-critical kv
        # grants for ~1.5s: while a kv acquire waits, every bulk chunk
        # grant on this peer must park (floor 20% < one chunk)
        t_end = time.monotonic() + 1.5
        while time.monotonic() < t_end and pt.is_alive():
            qos.acquire(src_label, "kv", 128 * 1024, owner="tenant-kv",
                        timeout=5.0)
        pt.join(timeout=120)
        assert pulled == [True], "preempted pull never completed"

        st = qos.stats(src_label)
        assert st["parks"]["bulk"] >= 1, st     # chunk grants parked
        assert st["preemptions"] >= 1, st       # ...while kv waited
        assert st["granted_bytes"]["kv"] > 0, st
        # byte-identical resume: parked chunks re-request the same
        # offset, never restart or corrupt the object
        buf = dst.store.get(oid)
        assert buf is not None and bytes(buf.data) == data
        buf.release()
        # attribution exact with the pacer on (<= 1% by acceptance;
        # the tally is byte-exact here)
        owner = wid.hex()[:12]
        assert net.total("rx", qos_class="bulk", owner=owner) == len(data)
        assert net.total("tx", qos_class="bulk", owner=owner) == len(data)
    finally:
        cfg.set_system_config({
            "object_transfer_chunk_bytes": old_chunk,
            "net_qos_rate_mbps": 0.0,
            "net_qos_window_bytes": 0,
        })
        qos.reset()


def test_scatter_pull_preempted_by_kv_resumes_byte_identical(agents_cluster):
    """Scatter-read × QoS: a bulk pull whose chunks scatter directly
    into the shm write buffer is preempted mid-transfer by a kv hammer
    (chunk-granularity park/resume), resumes byte-identically, keeps
    the zero-copy path for resumed chunks (scattered counter), and the
    byte attribution stays exact."""
    c = agents_cluster
    src, dst = c.agents[0], c.agents[1]
    src_label = src.node_id.hex()[:8]
    old_chunk = cfg.get("object_transfer_chunk_bytes")
    qos.reset()
    net.reset_local()
    try:
        cfg.set_system_config({
            "object_transfer_chunk_bytes": 256 * 1024,
            "net_qos_rate_mbps": 8.0,
            "net_qos_window_bytes": 256 * 1024,
            "transfer_scatter_read": True,
        })
        wid = bytes([0xCD]) * 16
        data = os.urandom(2 * 2**20)  # 8 chunks
        oid = _seed_owned(c, src, data, wid)

        pulled = []

        def pull():
            pulled.append(c.io.run(dst.rpc_fetch_object(
                None, {"object_id": oid, "timeout": 120})))

        pt = threading.Thread(target=pull)
        pt.start()
        time.sleep(0.2)  # mid-flight
        t_end = time.monotonic() + 1.5
        while time.monotonic() < t_end and pt.is_alive():
            qos.acquire(src_label, "kv", 128 * 1024, owner="tenant-kv",
                        timeout=5.0)
        pt.join(timeout=120)
        assert pulled == [True], "preempted scatter pull never completed"

        st = qos.stats(src_label)
        assert st["parks"]["bulk"] >= 1, st
        assert st["preemptions"] >= 1, st
        last = dst.transfer_stats["last_pull"]
        # park/resume kept the zero-copy receive path: resumed chunks
        # still scatter straight into the write buffer
        assert last["scattered"] == last["chunks"] - 1, last
        buf = dst.store.get(oid)
        assert buf is not None and bytes(buf.data) == data
        buf.release()
        owner = wid.hex()[:12]
        assert net.total("rx", qos_class="bulk", owner=owner) == len(data)
        assert net.total("tx", qos_class="bulk", owner=owner) == len(data)
    finally:
        cfg.set_system_config({
            "object_transfer_chunk_bytes": old_chunk,
            "net_qos_rate_mbps": 0.0,
            "net_qos_window_bytes": 0,
        })
        qos.reset()


def test_peer_death_purges_pacer_state(agents_cluster):
    """Chaos safety: a dead peer's exhausted window must not throttle a
    reused address forever — the node-death push purges it."""
    c = agents_cluster
    a, b = c.agents[0], c.agents[1]
    label = b.node_id.hex()[:8]
    qos.reset()
    cfg.set_system_config({"net_qos_rate_mbps": 0.8,
                           "net_qos_window_bytes": 100_000})
    try:
        assert qos.try_acquire(label, "bulk", 100_000) == 0.0
        assert qos.try_acquire(label, "bulk", 100_000) > 0  # exhausted
        assert qos.stats(label)

        async def fire():
            a._on_node_dead_push({"node_id": b.node_id})

        c.io.run(fire())
        assert qos.stats(label) == {}, "pacer state survived peer death"
        # no permanent throttle: the next acquire gets a fresh window
        assert qos.try_acquire(label, "bulk", 100_000) == 0.0
    finally:
        cfg.set_system_config({"net_qos_rate_mbps": 0.0,
                               "net_qos_window_bytes": 0})
        qos.reset()


# ---------------- serving pool (driver-connected cluster) -------------

@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 8 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_two_tenant_wfq_floors_cold_tenant_ttft(cluster):
    """A hot tenant flooding the admission queue cannot push another
    tenant's TTFT p99 past its floor: weighted fair queueing admits the
    cold tenant's sparse requests ahead of the hot backlog instead of
    FIFO-appending them behind it."""
    from ray_tpu.serve.llm_pool import LLMPool

    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=8, prompt_buckets=(16,),
                   min_replicas=1, max_replicas=1, chunk_delay_s=0.05,
                   autoscale=False,
                   tenant_weights={"hot": 1.0, "cold": 1.0})
    try:
        warm = [int(x) for x in
                np.random.RandomState(5).randint(1, 250, 16)]
        ray_tpu.get([r.handle.generate.remote(warm, 8)
                     for r in pool._alive()], timeout=600)
        n_hot, n_cold, new_tokens = 12, 2, 48
        errs: list[str] = []

        def one(i, tenant):
            rng = np.random.RandomState(7000 + i)
            prompt = [int(x) for x in rng.randint(1, 250, 16)]
            try:
                out = pool.generate(prompt, new_tokens, tenant=tenant)
                assert len(out["tokens"]) == new_tokens
            except Exception as e:  # noqa: BLE001
                errs.append(f"{tenant} {i}: {type(e).__name__}: {e}")

        hot = [threading.Thread(target=one, args=(i, "hot"))
               for i in range(n_hot)]
        cold = [threading.Thread(target=one, args=(100 + i, "cold"))
                for i in range(n_cold)]
        for t in hot:
            t.start()
        time.sleep(0.4)  # the hot backlog is queued and deep
        for t in cold:
            t.start()
        for t in hot + cold:
            t.join(timeout=300)
        assert not errs, errs[0]

        hot_p99 = pool.ttft_p99("hot")
        cold_p99 = pool.ttft_p99("cold")
        assert hot_p99 is not None and cold_p99 is not None
        # FIFO would serialize cold behind the ~12-deep hot backlog
        # (TTFT ~= the full drain ~= hot's worst case); WFQ admits it
        # within a round or two
        assert cold_p99 < 0.75 * hot_p99, (
            f"cold tenant TTFT p99 {cold_p99:.3f}s not floored vs "
            f"hot {hot_p99:.3f}s")
        by_tenant = pool.stats()["ttft_p99_by_tenant"]
        assert set(by_tenant) >= {"hot", "cold"}
    finally:
        pool.shutdown()


def test_batched_stream_polls_amortize_rpcs(cluster):
    """Satellite 1: co-located streams share one poll_streams RPC per
    poller round instead of one RPC per stream — and batching changes
    no tokens (greedy streams match the non-streaming output)."""
    from ray_tpu.serve.llm_pool import LLMPool

    pool = LLMPool(model_size="tiny", slots=4, max_len=96,
                   chunk_tokens=8, prompt_buckets=(16,),
                   min_replicas=1, max_replicas=1, chunk_delay_s=0.03,
                   autoscale=False)
    try:
        rng = np.random.RandomState(11)
        prompt = [int(x) for x in rng.randint(1, 250, 16)]
        new_tokens = 32
        ref = pool.generate(list(prompt), new_tokens)["tokens"]

        n_streams = 3
        client_polls = [0] * n_streams
        toks: list[list] = [[] for _ in range(n_streams)]

        rep = pool._alive()[0]
        polls0 = ray_tpu.get(rep.handle.stats.remote(),
                             timeout=60)["stream_polls"]

        def stream_one(i):
            sub = pool.submit_stream({"prompt_ids": list(prompt),
                                      "max_tokens": new_tokens})
            while True:
                out = pool.poll_stream(sub["rid"])
                client_polls[i] += 1
                toks[i] += out["tokens"]
                if out["done"]:
                    break
                time.sleep(0.01)

        threads = [threading.Thread(target=stream_one, args=(i,))
                   for i in range(n_streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        polls1 = ray_tpu.get(rep.handle.stats.remote(),
                             timeout=60)["stream_polls"]

        # greedy determinism: batched polling changed no tokens
        for i in range(n_streams):
            assert toks[i] == list(ref), f"stream {i} diverged"
        # the replica served fewer poll RPCs than the clients issued
        # polls: co-located streams rode shared batches
        assert sum(client_polls) > n_streams
        assert polls1 - polls0 < sum(client_polls), (
            f"replica RPCs {polls1 - polls0} not amortized vs "
            f"{sum(client_polls)} client polls")
    finally:
        pool.shutdown()
