"""Object store + runtime-foundation tests.

Mirrors the reference's plasma test strategy (plasma store gtests +
python/ray/tests/test_object_store.py): lifecycle, zero-copy, refcounts,
eviction, cross-process sharing, crash of an unsealed writer.
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from ray_tpu._private import rpc, serialization
from ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID
from ray_tpu.core.object_store import (
    ObjectExistsError,
    ObjectStoreClient,
    StoreFullError,
)

MB = 1024 * 1024


@pytest.fixture
def store():
    name = f"/rt_test_{os.getpid()}_{os.urandom(4).hex()}"
    s = ObjectStoreClient.create(name, 32 * MB, table_cap=1024)
    yield s
    s.close()


def test_put_get_roundtrip(store):
    oid = os.urandom(16)
    arr = np.arange(10000, dtype=np.float64)
    buf = store.create_object(oid, arr.nbytes, 3)
    np.frombuffer(buf.data, dtype=np.float64)[:] = arr
    buf.meta[:] = b"abc"
    buf.seal()

    got = store.get(oid)
    out = np.frombuffer(got.data, dtype=np.float64)
    np.testing.assert_array_equal(out, arr)
    assert got.metadata == b"abc"
    # zero-copy: same memory, not a copy
    assert out.base is not None


def test_get_absent_and_unsealed(store):
    assert store.get(os.urandom(16)) is None
    oid = os.urandom(16)
    buf = store.create_object(oid, 100)
    assert store.get(oid) is None  # unsealed not readable
    assert not store.contains(oid)
    buf.seal()
    assert store.contains(oid)


def test_double_create_raises(store):
    oid = os.urandom(16)
    store.put_bytes(oid, b"x")
    with pytest.raises(ObjectExistsError):
        store.create_object(oid, 10)


def test_refcount_blocks_delete(store):
    oid = os.urandom(16)
    store.put_bytes(oid, b"payload")
    got = store.get(oid)
    assert not store.delete(oid)  # pinned by reader
    got.release()
    assert store.delete(oid)
    assert store.get(oid) is None


def test_eviction_lru(store):
    first = os.urandom(16)
    store.put_bytes(first, b"a" * MB)
    # touch `first` so it's MRU, then fill the store
    store.get(first).release()
    ids = [os.urandom(16) for _ in range(40)]
    for i in ids:
        store.put_bytes(i, b"b" * MB)
    # store only holds 32MB: early fill objects evicted, latest present
    assert store.contains(ids[-1])
    assert store.used_bytes() <= store.capacity()


def test_pinned_never_evicted(store):
    oid = os.urandom(16)
    store.put_bytes(oid, b"a" * MB)
    store.pin(oid)
    for _ in range(40):
        store.put_bytes(os.urandom(16), b"b" * MB)
    assert store.contains(oid)
    store.pin(oid, False)


def test_too_large_raises(store):
    with pytest.raises(StoreFullError):
        store.create_object(os.urandom(16), 33 * MB)


def test_put_bytes_chunked(store):
    oid = os.urandom(16)
    store.put_bytes(oid, [b"ab", b"cd", memoryview(b"ef")])
    got = store.get(oid)
    assert bytes(got.data) == b"abcdef"


def _child_writer(name, oid):
    c = ObjectStoreClient.attach(name)
    arr = np.ones(1024, dtype=np.int32)
    buf = c.create_object(oid, arr.nbytes)
    np.frombuffer(buf.data, dtype=np.int32)[:] = arr
    buf.seal()
    c.close()


def test_cross_process(store):
    oid = os.urandom(16)
    p = mp.get_context("spawn").Process(
        target=_child_writer, args=(store.name, oid)
    )
    p.start()
    p.join(30)
    assert p.exitcode == 0
    got = store.get(oid)
    assert np.frombuffer(got.data, dtype=np.int32).sum() == 1024


def _child_dier(name, oid):
    c = ObjectStoreClient.attach(name)
    c.create_object(oid, 4096)
    os._exit(1)  # die with the object unsealed


def test_unsealed_writer_crash_abortable(store):
    oid = os.urandom(16)
    p = mp.get_context("spawn").Process(
        target=_child_dier, args=(store.name, oid)
    )
    p.start()
    p.join(30)
    assert not store.contains(oid)
    store.abort(oid)  # node agent cleanup path
    # slot is reusable afterwards
    store.put_bytes(oid, b"again")
    assert store.contains(oid)


# ---- IDs ----

def test_id_derivation_deterministic():
    job = JobID.from_random()
    t1 = TaskID.for_task(job, None, 1)
    t2 = TaskID.for_task(job, None, 1)
    assert t1 == t2
    assert TaskID.for_task(job, None, 2) != t1
    o1 = ObjectID.for_task_return(t1, 0)
    assert o1 == ObjectID.for_task_return(t1, 0)
    assert o1 != ObjectID.for_task_return(t1, 1)
    a = ActorID.from_random()
    assert TaskID.for_actor_task(a, 5) == TaskID.for_actor_task(a, 5)


def test_id_roundtrip():
    i = ObjectID.from_random()
    assert ObjectID.from_hex(i.hex()) == i
    assert not i.is_nil()
    assert ObjectID.nil().is_nil()


# ---- serialization ----

def test_serialization_oob_buffers():
    arr = np.arange(100000, dtype=np.float32)
    obj = {"a": arr, "b": [1, 2, "three"]}
    meta, bufs = serialization.dumps_oob(obj)
    # big array went out-of-band, not into the pickle stream
    assert len(meta) < 10000
    assert sum(len(memoryview(b)) for b in bufs) >= arr.nbytes
    back = serialization.loads_oob(meta, bufs)
    np.testing.assert_array_equal(back["a"], arr)
    assert back["b"] == obj["b"]


# ---- rpc ----

def test_rpc_roundtrip_and_push():
    io = rpc.EventLoopThread("test-io")

    server = rpc.RpcServer()

    async def echo(conn, payload):
        return {"echo": payload}

    async def boom(conn, payload):
        raise ValueError("kapow")

    server.handlers["echo"] = echo
    server.handlers["boom"] = boom
    port = io.run(server.start())

    client = rpc.SyncRpcClient("127.0.0.1", port, io)
    assert client.call("echo", [1, "x", b"bin"]) == {"echo": [1, "x", b"bin"]}

    with pytest.raises(rpc.RpcError, match="kapow"):
        client.call("boom")

    # server push
    got = []
    client.on_push("chan", got.append)
    io.run(_push_all(server, "chan", {"k": 1}))
    deadline = __import__("time").time() + 5
    while not got and __import__("time").time() < deadline:
        __import__("time").sleep(0.01)
    assert got == [{"k": 1}]

    client.close()
    io.run(server.stop())
    io.stop()


async def _push_all(server, chan, payload):
    for conn in server.conns:
        conn.push(chan, payload)
