"""Metrics API + dashboard head.

Reference test models: python/ray/tests/test_metrics_agent.py,
dashboard/tests — user metrics flow process -> head -> Prometheus text;
dashboard endpoints serve live cluster state.
"""

import http.client
import json
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.metrics import Counter, Gauge, Histogram, flush_once


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=60)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, body


def test_metric_types_validate():
    with pytest.raises(ValueError):
        Histogram("h_bad", boundaries=[])
    with pytest.raises(ValueError):
        Histogram("h_bad2", boundaries=[5, 1])
    c = Counter("c_neg")
    with pytest.raises(ValueError):
        c.inc(-1)


def test_metrics_flow_to_head(cluster):
    c = Counter("test_requests_total", description="reqs",
                tag_keys=("route",))
    g = Gauge("test_queue_depth")
    h = Histogram("test_latency_s", boundaries=[0.1, 1.0])
    c.inc(3, tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    g.set(7)
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    flush_once()
    w = ray_tpu._private.api._get_worker()
    rows = w.head.call("get_metrics", {})
    by_name = {}
    for r in rows:
        by_name.setdefault(r["name"], []).append(r)
    assert sum(r["value"] for r in by_name["test_requests_total"]) == 5
    assert any(r["value"] == 7 for r in by_name["test_queue_depth"])
    lat = {tuple(map(tuple, r["tags"])): r["value"]
           for r in by_name["test_latency_s"]}
    assert lat[(("le", "0.1"),)] == 1
    assert lat[(("le", "1.0"),)] == 2
    assert lat[(("le", "+Inf"),)] == 3


def test_metrics_from_remote_task(cluster):
    @ray_tpu.remote
    def emit():
        from ray_tpu.util.metrics import Counter, flush_once

        Counter("task_side_metric").inc(11)
        flush_once()
        return True

    assert ray_tpu.get(emit.remote())
    w = ray_tpu._private.api._get_worker()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        rows = w.head.call("get_metrics", {})
        vals = [r["value"] for r in rows if r["name"] == "task_side_metric"]
        if vals == [11]:
            return
        time.sleep(0.2)
    raise AssertionError(f"task metric never arrived: {rows}")


def test_dashboard_endpoints(cluster):
    from ray_tpu.dashboard import start_dashboard

    Counter("dash_metric").inc(4)
    flush_once()
    addr = start_dashboard()

    status, body = _get(addr, "/api/cluster")
    assert status == 200
    summary = json.loads(body)
    assert summary["nodes_alive"] >= 1
    assert summary["cpus_total"] >= 4

    status, body = _get(addr, "/api/nodes")
    nodes = json.loads(body)
    assert status == 200 and len(nodes) >= 1
    # reporter stats ride heartbeats; wait for one carrying psutil stats
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        nodes = json.loads(_get(addr, "/api/nodes")[1])
        if any("mem_total" in (n.get("stats") or {}) for n in nodes):
            break
        time.sleep(0.5)
    assert any("mem_total" in (n.get("stats") or {}) for n in nodes)

    status, body = _get(addr, "/api/actors")
    assert status == 200

    status, body = _get(addr, "/metrics")
    text = body.decode()
    assert status == 200
    assert "ray_tpu_cluster_nodes_alive" in text
    assert "dash_metric 4" in text or "dash_metric" in text

    status, body = _get(addr, "/api/nope")
    assert status == 404


def test_slo_speculation_acceptance_block(cluster):
    """ISSUE-19 satellite: /api/slo aggregates the speculative-decode
    counter pair into a per-engine acceptance block, so an operator can
    see whether the draft model is earning its verify cost."""
    from ray_tpu.dashboard import start_dashboard

    Counter("decode_engine_spec_proposed_total",
            tag_keys=("engine",)).inc(40, {"engine": "decode-9"})
    Counter("decode_engine_spec_accepted_total",
            tag_keys=("engine",)).inc(25, {"engine": "decode-9"})
    flush_once()
    addr = start_dashboard()
    deadline = time.monotonic() + 15
    spec = {}
    while time.monotonic() < deadline:
        status, body = _get(addr, "/api/slo")
        assert status == 200
        spec = json.loads(body).get("speculation", {})
        if "decode-9" in spec:
            break
        time.sleep(0.2)
    ent = spec["decode-9"]
    assert ent["proposed"] >= 40 and ent["accepted"] >= 25
    assert 0.0 < ent["acceptance_rate"] <= 1.0


def test_dashboard_stacks(cluster):
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def parked():
        time.sleep(8)
        return True

    ref = parked.remote()
    time.sleep(1.0)  # let it start
    addr = start_dashboard()
    status, body = _get(addr, "/api/stacks")
    assert status == 200
    dumps = json.loads(body)
    text = json.dumps(dumps)
    assert "parked" in text or "time.sleep" in text
    ray_tpu.get(ref, timeout=30)


def test_dashboard_logs(cluster):
    """Per-worker log files + /api/logs listing and tailing (reference
    log_monitor + dashboard/modules/log)."""
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def chatty():
        print("hello-from-worker-stdout")
        import sys

        print("warn-on-stderr", file=sys.stderr)
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=60) == 1
    addr = start_dashboard()

    # listing: at least one node exposes worker-*.out files
    deadline = time.monotonic() + 20
    listing = []
    while time.monotonic() < deadline:
        status, body = _get(addr, "/api/logs")
        assert status == 200
        listing = json.loads(body)
        files = [f for n in listing for f in n["files"]
                 if isinstance(f, dict)]
        if any(f["file"].endswith(".out") and f["bytes"] > 0
               for f in files):
            break
        time.sleep(0.3)
    node = next(n for n in listing
                if any(isinstance(f, dict) and f["file"].endswith(".out")
                       and f["bytes"] > 0 for f in n["files"]))
    # find the file containing our line (several pool workers may exist)
    found = False
    for f in node["files"]:
        if not f["file"].endswith(".out"):
            continue
        status, body = _get(
            addr, f"/api/logs?node_id={node['node_id']}&file={f['file']}")
        assert status == 200
        tail = json.loads(body)
        if "hello-from-worker-stdout" in tail["data"]:
            found = True
            break
    assert found, "worker stdout line not served via /api/logs"


def test_dashboard_profile(cluster):
    """On-demand statistical CPU profiling across workers (reference
    reporter_agent CpuProfiling / py-spy analog)."""
    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def started():
        return True

    @ray_tpu.remote
    def burn():
        import time as t

        end = t.time() + 8.0
        x = 0
        while t.time() < end:
            x += 1
        return x

    # readiness: a task completing means a worker exists and the queue
    # has drained to `burn` — the sample window then overlaps it
    ray_tpu.get(started.remote(), timeout=60)
    ref = burn.remote()
    time.sleep(0.5)  # let burn dispatch
    addr = start_dashboard()
    status, body = _get(addr, "/api/profile?duration=1.5")
    assert status == 200
    nodes = json.loads(body)
    samples = {}
    for n in nodes:
        for w in n.get("workers", []):
            samples.update(w.get("samples", {}))
    assert samples, "no profile samples collected"
    # the busy loop shows up in some collapsed stack
    assert any("burn" in k for k in samples), list(samples)[:3]
    assert ray_tpu.get(ref, timeout=60) > 0
