"""runtime_env URI packaging + per-node cache + GC (VERDICT Missing #10:
the working_dir/py_modules depth beyond raw same-host paths)."""

import os

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_working_dir_packaged_as_uri(cluster, tmp_path):
    """A local working_dir ships as a pkg:// URI through the cluster KV
    and extracts into the node package cache — the worker's cwd is the
    CACHE COPY, not the driver's path."""
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "data.txt").write_text("hello-from-package")
    (proj / "helper_mod_xyz.py").write_text(
        "VALUE = 'imported-from-package'\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(proj)})
    def read_both():
        import os

        import helper_mod_xyz  # importable: cwd/PYTHONPATH include pkg

        with open("data.txt") as f:
            return f.read(), helper_mod_xyz.VALUE, os.getcwd()

    data, val, cwd = ray_tpu.get(read_both.remote(), timeout=120)
    assert data == "hello-from-package"
    assert val == "imported-from-package"
    assert str(proj) not in cwd  # ran from the extracted cache copy
    assert "ray_tpu_pkgs_" in cwd

    # the URI is cached + refcounted on the agent
    cache = cluster.head_agent.pkg_cache
    assert cache._refs or cache._idle_since  # known to the cache


def test_same_dir_uploads_once(cluster, tmp_path):
    proj = tmp_path / "proj2"
    proj.mkdir()
    (proj / "x.txt").write_text("v1")

    from ray_tpu._private.runtime_env import PKG_SCHEME, package_local_dirs

    w = cluster._driver
    env1 = package_local_dirs({"working_dir": str(proj)}, w.head)
    env2 = package_local_dirs({"working_dir": str(proj)}, w.head)
    assert env1["working_dir"].startswith(PKG_SCHEME)
    assert env1 == env2  # content-addressed: identical URI, one upload

    (proj / "x.txt").write_text("v2")
    env3 = package_local_dirs({"working_dir": str(proj)}, w.head)
    assert env3["working_dir"] != env1["working_dir"]  # content changed


def test_cache_gc_evicts_idle_uris(tmp_path):
    from ray_tpu._private import runtime_env as re_mod
    from ray_tpu._private.runtime_env import PKG_SCHEME, PackageCache

    cache = PackageCache(str(tmp_path / "cache"))
    import io
    import zipfile

    def mkzip():
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as z:
            z.writestr("f.txt", "x")
        return buf.getvalue()

    uris = [f"{PKG_SCHEME}uri{i}" for i in range(re_mod.IDLE_CACHE_KEEP + 3)]
    for u in uris:
        cache.extract(u, mkzip())
        cache.acquire(u)
    for u in uris:
        cache.release(u)
    # only the keep-cap newest-idle extractions survive
    surviving = [u for u in uris if cache.dir_if_present(u)]
    assert len(surviving) == re_mod.IDLE_CACHE_KEEP
    assert surviving == uris[-re_mod.IDLE_CACHE_KEEP:]


def test_edited_working_dir_repackages(cluster, tmp_path):
    """Editing files under a memoized working_dir ships the NEW content
    on the next submission (stat-fingerprint memo key)."""
    import time as _t

    proj = tmp_path / "editable"
    proj.mkdir()
    (proj / "v.txt").write_text("first")

    @ray_tpu.remote(runtime_env={"working_dir": str(proj)})
    def read():
        return open("v.txt").read()

    assert ray_tpu.get(read.remote(), timeout=120) == "first"
    _t.sleep(0.01)  # ensure mtime_ns moves
    (proj / "v.txt").write_text("second")
    assert ray_tpu.get(read.remote(), timeout=120) == "second"
