"""Overload guardian (serve/overload.py): hermetic ladder units.

ISSUE-20 tentpole, no-cluster half: hysteretic L0-L3 ladder mechanics
(monotonic escalation, one level per dwell, hold-band no-flap,
hysteretic recovery), deadline-aware admission semantics against a stub
pool, bounded checkpoint-ship deferral, PoolActions config
save/restore, and chaos-plan determinism (existing profiles stay
byte-identical with the colocate profile added)."""

import collections
import threading
import time

import pytest

from ray_tpu._private import config as _cfg
from ray_tpu._private import fault_injection as fi
from ray_tpu.serve import overload as ov
from ray_tpu.serve.overload import (
    L0_HEALTHY,
    L1_SHED_SPECULATION,
    L2_SQUEEZE_BULK,
    L3_SHED_ADMISSION,
    DeadlineExceededError,
    OverloadGuardian,
    PoolOverloadedError,
)


@pytest.fixture(autouse=True)
def _clean():
    fi.clear()
    ov._set_bulk_deferral(False)
    yield
    fi.clear()
    ov._set_bulk_deferral(False)


class _Acts:
    """Recording actions object: the guardian's side-effect log."""

    def __init__(self):
        self.calls = []

    def shed_speculation(self, engage):
        self.calls.append(("spec", engage))

    def squeeze_bulk(self, engage):
        self.calls.append(("bulk", engage))

    def shed_admission(self, engage):
        self.calls.append(("adm", engage))


HOT = {"queue_per_replica": 99.0, "ttft_p99_s": None,
       "target_ttft_s": None, "tokens_per_s": 0.0,
       "link_saturation": 0.0}
COOL = {"queue_per_replica": 0.0, "ttft_p99_s": None,
        "target_ttft_s": None, "tokens_per_s": 0.0,
        "link_saturation": 0.0}


def _guardian():
    t = [0.0]
    acts = _Acts()
    g = OverloadGuardian(actions=acts, clock=lambda: t[0])
    return g, acts, t


# ---------------------------------------------------------------------------
# ladder mechanics
# ---------------------------------------------------------------------------


def test_ladder_escalates_one_level_per_dwell():
    g, acts, t = _guardian()
    dwell = float(_cfg.get("overload_escalate_dwell_s"))
    # sub-dwell pressure never moves the ladder
    t[0] += dwell * 0.5
    assert g.tick(HOT) == L0_HEALTHY
    # each full dwell of sustained pressure buys exactly ONE level
    for want in (L1_SHED_SPECULATION, L2_SQUEEZE_BULK,
                 L3_SHED_ADMISSION):
        t[0] += dwell + 0.01
        assert g.tick(HOT) == want
    # L3 is the ceiling
    t[0] += dwell * 3
    assert g.tick(HOT) == L3_SHED_ADMISSION
    assert acts.calls == [("spec", True), ("bulk", True), ("adm", True)]
    assert [x["to"] for x in g.transitions] == ["L1", "L2", "L3"]


def test_ladder_recovery_is_hysteretic_and_restores():
    g, acts, t = _guardian()
    esc = float(_cfg.get("overload_escalate_dwell_s"))
    rec = float(_cfg.get("overload_recover_dwell_s"))
    g.tick(HOT)  # arm the pressure timer
    for _ in range(3):
        t[0] += esc + 0.01
        g.tick(HOT)
    assert g.level == L3_SHED_ADMISSION
    acts.calls.clear()
    # calm shorter than the recovery dwell does not descend
    g.tick(COOL)  # arm the calm timer
    t[0] += rec * 0.5
    assert g.tick(COOL) == L3_SHED_ADMISSION
    for want in (L2_SQUEEZE_BULK, L1_SHED_SPECULATION, L0_HEALTHY):
        t[0] += rec + 0.01
        assert g.tick(COOL) == want
    # disengage order mirrors engage order, outermost level first
    assert acts.calls == [("adm", False), ("bulk", False),
                          ("spec", False)]


def test_ladder_hold_band_never_flaps():
    """A signal oscillating inside the dead band (below the escalate
    watermark, above the recovery watermark) freezes the ladder: no
    transition in either direction, ever."""
    g, acts, t = _guardian()
    esc = float(_cfg.get("overload_escalate_dwell_s"))
    q_high = float(_cfg.get("overload_queue_per_replica_high"))
    frac = float(_cfg.get("overload_recovery_fraction"))
    g.tick(HOT)  # arm
    t[0] += esc + 0.01
    g.tick(HOT)
    assert g.level == L1_SHED_SPECULATION
    n0 = len(g.transitions)
    mid = dict(COOL)
    mid["queue_per_replica"] = q_high * (frac + 1.0) / 2.0  # dead band
    for _ in range(50):
        t[0] += 7.0  # far past both dwells
        g.tick(mid)
    assert g.level == L1_SHED_SPECULATION
    assert len(g.transitions) == n0  # zero flaps
    # the hold also resets accumulated heat: one hot tick after a long
    # hold must not instantly escalate
    g.tick(HOT)
    assert g.level == L1_SHED_SPECULATION


def test_ladder_disabled_by_config():
    g, acts, t = _guardian()
    _cfg.set_system_config({"overload_enabled": False})
    try:
        for _ in range(10):
            t[0] += 5.0
            assert g.tick(HOT) == L0_HEALTHY
        assert acts.calls == []
    finally:
        _cfg.set_system_config({"overload_enabled": True})


def test_ttft_breach_is_escalation_pressure():
    g, acts, t = _guardian()
    esc = float(_cfg.get("overload_escalate_dwell_s"))
    sig = dict(COOL)
    sig["ttft_p99_s"], sig["target_ttft_s"] = 2.0, 0.5
    g.tick(sig)  # arm
    t[0] += esc + 0.01
    assert g.tick(sig) == L1_SHED_SPECULATION


def test_transitions_recorded_in_flight_recorder():
    from ray_tpu._private import flight_recorder as _fr

    g, acts, t = _guardian()
    t[0] = time.monotonic()  # recorder clamps spans to real time
    g.tick(HOT)  # arm
    t[0] += float(_cfg.get("overload_escalate_dwell_s")) + 0.01
    g.tick(HOT)
    spans = [s for s in _fr._get().ring
             if s.get("name") == "overload.transition"]
    assert spans, "transition must leave a flight-recorder span"
    attrs = spans[-1].get("attrs", {})
    assert attrs.get("from") == "L0" and attrs.get("to") == "L1"
    assert "queue_per_replica" in attrs


# ---------------------------------------------------------------------------
# checkpoint-ship deferral (the L2 hook train/checkpoint.py consults)
# ---------------------------------------------------------------------------


def test_bulk_deferral_engage_disengage_and_bound():
    assert not ov.bulk_deferred()
    assert ov.wait_bulk_clearance() == 0.0  # healthy fast path
    ov._set_bulk_deferral(True)
    assert ov.bulk_deferred()
    t0 = time.monotonic()
    waited = ov.wait_bulk_clearance(max_wait_s=0.3, poll_s=0.02)
    assert 0.25 <= waited <= 2.0  # bounded: gives up, never parks
    assert time.monotonic() - t0 < 2.0
    ov._set_bulk_deferral(False)
    assert not ov.bulk_deferred()


def test_bulk_deferral_decays_without_guardian_refresh():
    """The horizon is a decaying timestamp, not a latched flag: a dead
    guardian cannot park checkpoint shipping forever."""
    _cfg.set_system_config({"overload_ship_defer_max_s": 0.01})
    try:
        ov._set_bulk_deferral(True)
        # floor of the horizon is 2s; it expires on its own
        assert ov.bulk_deferred()
        assert ov._bulk_defer_until <= time.monotonic() + 2.5
    finally:
        _cfg.set_system_config({"overload_ship_defer_max_s": 15.0})
        ov._set_bulk_deferral(False)


# ---------------------------------------------------------------------------
# PoolActions: driver-config engage saves + restores operator values
# ---------------------------------------------------------------------------


def test_pool_actions_save_and_restore_operator_config():
    acts = ov.PoolActions(None)  # driver-only (no replica broadcast)
    _cfg.set_system_config({"serve_spec_enabled": True,
                            "net_qos_bulk_share": 0.2})
    try:
        acts.shed_speculation(True)
        assert _cfg.get("serve_spec_enabled") is False
        acts.squeeze_bulk(True)
        assert float(_cfg.get("net_qos_bulk_share")) == pytest.approx(
            float(_cfg.get("overload_bulk_share_squeezed")))
        assert ov.bulk_deferred()
        acts.squeeze_bulk(False)
        assert float(_cfg.get("net_qos_bulk_share")) == 0.2
        assert not ov.bulk_deferred()
        acts.shed_speculation(False)
        assert _cfg.get("serve_spec_enabled") is True
    finally:
        _cfg.set_system_config({"serve_spec_enabled": True,
                                "net_qos_bulk_share": 0.2})
        ov._set_bulk_deferral(False)


def test_pool_actions_respect_operator_off():
    """An operator who runs with speculation OFF must not have it
    flipped ON by a guardian recovery."""
    acts = ov.PoolActions(None)
    _cfg.set_system_config({"serve_spec_enabled": False})
    try:
        acts.shed_speculation(True)
        acts.shed_speculation(False)
        assert _cfg.get("serve_spec_enabled") is False
    finally:
        _cfg.set_system_config({"serve_spec_enabled": True})


# ---------------------------------------------------------------------------
# typed errors + deadline-aware admission against a stub pool
# ---------------------------------------------------------------------------


def test_pool_overloaded_error_is_typed_retryable():
    e = PoolOverloadedError("tenantB", "low_weight", 1.5)
    assert e.retryable is True
    assert e.tenant == "tenantB" and e.reason == "low_weight"
    assert e.retry_after_s == 1.5
    assert isinstance(e, RuntimeError)
    d = DeadlineExceededError("a", "deadline", 0.7)
    assert d.retryable is True and isinstance(d, PoolOverloadedError)


class _StubPool:
    """Just the state _admission_shed/_shed/_admit_rate_locked touch —
    the admission gate unit-tested without spawning replica actors."""

    TTFT_WINDOW_S = 30.0

    def __init__(self, waiting=0, weights=None, level=L0_HEALTHY):
        self._lock = threading.Lock()
        self._waiting = waiting
        self._admits = collections.deque(maxlen=256)
        self._tenant_weights = dict(weights or {})

        class _G:
            pass

        self._guardian = _G()
        self._guardian.level = level

    def seed_rate(self, per_s, n=20):
        now = time.monotonic()
        for i in range(n):
            self._admits.append(now - (n - 1 - i) / per_s)

    def _admit_rate_locked(self, now):
        from ray_tpu.serve.llm_pool import LLMPool

        return LLMPool._admit_rate_locked(self, now)


from ray_tpu.serve.llm_pool import LLMPool  # noqa: E402


def _shed_of(pool, tenant, deadline_abs=None):
    return LLMPool._admission_shed(pool, tenant, deadline_abs)


def test_deadline_fast_fail_predicts_from_observed_rate():
    # 10 admissions/s observed, 50 already waiting -> ~5.1s predicted
    p = _StubPool(waiting=50)
    p.seed_rate(10.0)
    out = _shed_of(p, "a", deadline_abs=time.monotonic() + 1.0)
    assert out is not None
    reason, retry, exc = out
    assert reason == "deadline" and exc is DeadlineExceededError
    assert retry > 1.0  # the hint reflects the predicted wait
    # a meetable deadline admits
    assert _shed_of(p, "a", deadline_abs=time.monotonic() + 60) is None


def test_deadline_cold_pool_never_fast_fails_on_a_guess():
    p = _StubPool(waiting=50)  # no admission history -> no rate
    assert _shed_of(p, "a", deadline_abs=time.monotonic() + 0.1) is None


def test_l3_sheds_lowest_weight_first_then_everyone():
    bound = int(_cfg.get("overload_shed_queue_bound"))
    weights = {"gold": 4.0, "bronze": 1.0}
    # below every threshold: nobody sheds even at L3
    p = _StubPool(waiting=2, weights=weights, level=L3_SHED_ADMISSION)
    assert _shed_of(p, "gold") is None
    assert _shed_of(p, "bronze") is None
    # mid-queue: bronze (weight share 1/4) sheds, gold rides on
    mid = int(bound * 0.6)
    p = _StubPool(waiting=mid, weights=weights, level=L3_SHED_ADMISSION)
    p.seed_rate(5.0)
    assert _shed_of(p, "gold") is None
    out = _shed_of(p, "bronze")
    assert out is not None
    reason, retry, exc = out
    assert reason == "low_weight" and exc is PoolOverloadedError
    assert retry >= float(_cfg.get("overload_retry_after_min_s"))
    # over the hard bound: every tenant sheds
    p = _StubPool(waiting=bound + 5, weights=weights,
                  level=L3_SHED_ADMISSION)
    for tn in ("gold", "bronze"):
        out = _shed_of(p, tn)
        assert out is not None and out[0] == "queue_bound"


def test_below_l3_never_sheds_regardless_of_queue():
    p = _StubPool(waiting=10_000, weights={"a": 1.0},
                  level=L2_SQUEEZE_BULK)
    assert _shed_of(p, "a") is None


def test_shed_raises_typed_and_counts():
    p = _StubPool(level=L3_SHED_ADMISSION)
    with pytest.raises(PoolOverloadedError) as ei:
        LLMPool._shed(p, "bronze", "queue_bound", 2.0,
                      PoolOverloadedError)
    assert ei.value.retryable and ei.value.retry_after_s == 2.0
    assert ei.value.level == L3_SHED_ADMISSION


def test_chaos_drop_suppresses_the_shed():
    """The ``overload.shed`` site's ``drop`` action admits the request
    anyway — the colocate chaos plan exercises the queue-bound
    backstop through it."""
    p = _StubPool(level=L3_SHED_ADMISSION)
    fi.configure([{"site": "overload.shed", "action": "drop",
                   "count": 1}])
    LLMPool._shed(p, "bronze", "queue_bound", 2.0,
                  PoolOverloadedError)  # no raise: suppressed
    assert fi.hits() and fi.hits()[0]["site"] == "overload.shed"
    # the injection is exhausted -> the next shed is real
    with pytest.raises(PoolOverloadedError):
        LLMPool._shed(p, "bronze", "queue_bound", 2.0,
                      PoolOverloadedError)


def test_admit_rate_window():
    p = _StubPool()
    now = time.monotonic()
    assert LLMPool._admit_rate_locked(p, now) is None  # cold
    p.seed_rate(8.0, n=16)
    rate = LLMPool._admit_rate_locked(p, now)
    assert rate == pytest.approx(8.0, rel=0.3)


# ---------------------------------------------------------------------------
# chaos-plan determinism (satellite: colocate added, legacy plans frozen)
# ---------------------------------------------------------------------------

# env_value() captured at the commit BEFORE the colocate profile was
# added: the soak suites replay these exact seeds, so plan generation
# must stay byte-identical for every legacy profile.
GOLDEN_PLANS = {
    ("train", 1): '[{"action": "delay", "after": 4, "count": 1, "delay_s": 0.079, "match": {"rank": 0}, "site": "collective.send"}]',  # noqa: E501
    ("train", 2): '[{"action": "exit", "after": 4, "count": 1, "match": {"rank": 0}, "site": "ring.send"}]',  # noqa: E501
    ("train", 3): '[{"action": "die", "after": 9, "count": 1, "match": {"rank": 1}, "site": "collective.send"}]',  # noqa: E501
    ("train", 38): '[{"action": "exit", "after": 5, "count": 1, "match": {"rank": 0}, "site": "ring.recv"}, {"action": "drop", "after": 1, "count": 1, "site": "checkpoint.save"}]',  # noqa: E501
    ("train", 47): '[{"action": "die", "after": 4, "count": 1, "match": {"rank": 1}, "site": "ring.send"}, {"action": "die", "after": 6, "count": 1, "match": {"rank": 0}, "site": "collective.send"}]',  # noqa: E501
    ("train", 59): '[{"action": "die", "after": 0, "count": 1, "match": {"rank": 1}, "site": "ring.send"}]',  # noqa: E501
    ("rl", 1): '[{"action": "drop", "after": 4, "count": 1, "match": {"rank": 0}, "site": "ring.send"}]',  # noqa: E501
    ("rl", 2): '[{"action": "exit", "after": 99, "count": 1, "match": {"engine": "decode-1"}, "site": "serve.replica_pump"}]',  # noqa: E501
    ("rl", 3): '[{"action": "exit", "after": 9, "count": 1, "match": {"rank": 1}, "site": "ring.send"}]',  # noqa: E501
    ("rl", 38): '[{"action": "delay", "after": 5, "count": 1, "delay_s": 0.224, "match": {"actor": 0}, "site": "rl.rollout"}, {"action": "delay", "after": 5, "count": 1, "delay_s": 0.132, "match": {"actor": 0}, "site": "rl.rollout"}]',  # noqa: E501
    ("rl", 47): '[{"action": "exit", "after": 6, "count": 1, "match": {"rank": 0}, "site": "ring.send"}, {"action": "exit", "after": 37, "count": 1, "match": {"engine": "decode-2"}, "site": "serve.replica_pump"}]',  # noqa: E501
    ("rl", 59): '[{"action": "exit", "after": 7, "count": 1, "match": {"engine": "decode-2"}, "site": "serve.replica_pump"}]',  # noqa: E501
    ("qos", 1): '[{"action": "delay", "after": 0, "count": 1, "delay_s": 0.114, "site": "object.read_chunk"}]',  # noqa: E501
    ("qos", 2): '[{"action": "drop", "after": 1, "count": 1, "site": "net.pace"}]',  # noqa: E501
    ("qos", 3): '[{"action": "drop", "after": 4, "count": 1, "site": "object.read_chunk"}]',  # noqa: E501
    ("qos", 38): '[{"action": "delay", "after": 0, "count": 1, "delay_s": 0.142, "site": "net.pace"}, {"action": "drop", "after": 5, "count": 1, "site": "object.read_chunk"}]',  # noqa: E501
    ("qos", 47): '[{"action": "delay", "after": 4, "count": 1, "delay_s": 0.136, "site": "net.pace"}, {"action": "drop", "after": 0, "count": 1, "site": "object.read_chunk"}]',  # noqa: E501
    ("qos", 59): '[{"action": "delay", "after": 3, "count": 1, "delay_s": 0.056, "site": "net.pace"}]',  # noqa: E501
    ("pipeline", 1): '[{"action": "drop", "after": 4, "count": 1, "match": {"rank": 0}, "site": "ring.send"}]',  # noqa: E501
    ("pipeline", 2): '[{"action": "die", "after": 4, "count": 1, "match": {"rank": 0}, "site": "pipeline.stage"}]',  # noqa: E501
    ("pipeline", 3): '[{"action": "exit", "after": 9, "count": 1, "match": {"rank": 1}, "site": "ring.send"}]',  # noqa: E501
    ("pipeline", 38): '[{"action": "delay", "after": 5, "count": 1, "delay_s": 0.224, "match": {"rank": 0}, "site": "pipeline.stage"}, {"action": "exit", "after": 9, "count": 1, "match": {"rank": 0}, "site": "pipeline.stage"}]',  # noqa: E501
    ("pipeline", 47): '[{"action": "exit", "after": 4, "count": 1, "match": {"rank": 1}, "site": "pipeline.stage"}, {"action": "exit", "after": 6, "count": 1, "match": {"rank": 0}, "site": "ring.send"}]',  # noqa: E501
    ("pipeline", 59): '[{"action": "delay", "after": 0, "count": 1, "delay_s": 0.084, "match": {"rank": 1}, "site": "pipeline.stage"}]',  # noqa: E501
}


def test_legacy_chaos_plans_byte_identical():
    from ray_tpu._private.chaos import gen_fault_plan

    for (profile, seed), want in GOLDEN_PLANS.items():
        got = gen_fault_plan(
            seed, world_size=2, max_faults=2, profile=profile,
            n_replicas=2, n_prefill=0, n_rollout=1).env_value()
        assert got == want, (profile, seed)


def test_colocate_plans_deterministic_and_scoped():
    import json

    from ray_tpu._private.chaos import (
        COLOCATE_SITE_WEIGHTS,
        gen_fault_plan,
    )

    sites = set()
    for seed in range(80):
        p = gen_fault_plan(seed, world_size=2, max_faults=2,
                           profile="colocate", n_replicas=2)
        q = gen_fault_plan(seed, world_size=2, max_faults=2,
                           profile="colocate", n_replicas=2)
        assert p.env_value() == q.env_value()
        for spec in (p.worker_specs + p.driver_specs + p.serve_specs):
            sites.add(spec["site"])
    assert sites <= set(COLOCATE_SITE_WEIGHTS)
    assert "overload.shed" in sites  # the new site is reachable
    # legacy profiles never draw the new site
    for profile in ("train", "rl", "qos", "pipeline"):
        for seed in range(80):
            assert "overload.shed" not in gen_fault_plan(
                seed, world_size=2, max_faults=2, profile=profile,
                n_replicas=2).env_value()


def test_overload_shed_routes_to_driver_specs():
    from ray_tpu._private.chaos import DRIVER_SITES, gen_fault_plan

    assert "overload.shed" in DRIVER_SITES
    for seed in range(200):
        p = gen_fault_plan(seed, world_size=2, max_faults=2,
                           profile="colocate", n_replicas=2)
        for spec in p.worker_specs + p.serve_specs:
            assert spec["site"] != "overload.shed"
