"""Autoscaler tests with the local (fake-multinode) provider.

Reference analog: tests/test_autoscaler_fake_multinode.py, scaled: queued
demand scales nodes up; idle nodes scale back down.
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, LocalNodeProvider
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(head_resources={"CPU": 1, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_scale_up_then_down(cluster):
    scaler = Autoscaler(
        cluster._driver.head,
        LocalNodeProvider(cluster),
        AutoscalerConfig(
            min_workers=0, max_workers=2,
            worker_resources={"CPU": 2, "memory": 2 * 2**30},
            idle_timeout_s=2.0, poll_interval_s=0.5,
        ),
    )
    scaler.start()
    try:

        @ray_tpu.remote(num_cpus=2)  # cannot fit on the 1-CPU head node
        def heavy(i):
            import time as _t

            _t.sleep(1.0)
            return i

        refs = [heavy.remote(i) for i in range(4)]
        # demand forces scale-up beyond the head node
        deadline = time.time() + 60
        while time.time() < deadline and len(cluster.agents) < 2:
            time.sleep(0.2)
        assert len(cluster.agents) >= 2
        assert sorted(ray_tpu.get(refs, timeout=120)) == [0, 1, 2, 3]

        # idle nodes terminate back down to min_workers
        deadline = time.time() + 60
        while time.time() < deadline and len(cluster.agents) > 1:
            time.sleep(0.5)
        assert len(cluster.agents) == 1  # just the head node remains
    finally:
        scaler.stop()
