"""Autoscaler tests with the local (fake-multinode) provider.

Reference analog: tests/test_autoscaler_fake_multinode.py, scaled: queued
demand scales nodes up; idle nodes scale back down.
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, LocalNodeProvider
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(head_resources={"CPU": 1, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_scale_up_then_down(cluster):
    scaler = Autoscaler(
        cluster._driver.head,
        LocalNodeProvider(cluster),
        AutoscalerConfig(
            min_workers=0, max_workers=2,
            worker_resources={"CPU": 2, "memory": 2 * 2**30},
            idle_timeout_s=2.0, poll_interval_s=0.5,
        ),
    )
    scaler.start()
    try:

        @ray_tpu.remote(num_cpus=2)  # cannot fit on the 1-CPU head node
        def heavy(i):
            import time as _t

            _t.sleep(1.0)
            return i

        refs = [heavy.remote(i) for i in range(4)]
        # demand forces scale-up beyond the head node
        deadline = time.time() + 60
        while time.time() < deadline and len(cluster.agents) < 2:
            time.sleep(0.2)
        assert len(cluster.agents) >= 2
        assert sorted(ray_tpu.get(refs, timeout=120)) == [0, 1, 2, 3]

        # idle nodes terminate back down to min_workers
        deadline = time.time() + 60
        while time.time() < deadline and len(cluster.agents) > 1:
            time.sleep(0.5)
        assert len(cluster.agents) == 1  # just the head node remains
    finally:
        scaler.stop()


def test_demand_scheduler_bin_packing():
    """Pure bin-packing unit tests (reference resource_demand_scheduler)."""
    from ray_tpu.autoscaler.demand_scheduler import get_nodes_to_launch

    types = {
        "cpu-small": {"resources": {"CPU": 4.0}, "max_workers": 10},
        "cpu-big": {"resources": {"CPU": 16.0}, "max_workers": 2},
        "tpu-v5e-8": {"resources": {"TPU": 8.0, "CPU": 112.0,
                                    "tpu-slice:v5e-8": 1.0},
                      "max_workers": 4},
    }

    # fits on free capacity -> nothing launched
    assert get_nodes_to_launch([{"CPU": 2.0}], types,
                               [{"CPU": 8.0}]) == {}
    # 4-CPU task in a cluster of busy 2-CPU nodes -> exactly one small node
    assert get_nodes_to_launch([{"CPU": 4.0}], types,
                               [{"CPU": 2.0}, {"CPU": 2.0}]) == {
        "cpu-small": 1}
    # 6 x 2-CPU tasks -> pack into small nodes, not one per task
    assert get_nodes_to_launch([{"CPU": 2.0}] * 6, types, []) == {
        "cpu-small": 3}
    # a 12-CPU demand needs the big type (small can't hold it)
    assert get_nodes_to_launch([{"CPU": 12.0}], types, []) == {"cpu-big": 1}
    # per-type max respected
    assert get_nodes_to_launch([{"CPU": 12.0}] * 5, types, []) == {
        "cpu-big": 2}
    # unfittable demand launches nothing
    assert get_nodes_to_launch([{"GPU": 1.0}], types, []) == {}


def test_tpu_slice_pg_triggers_exact_launch():
    """A pending STRICT_PACK TPU-slice PG maps to exactly ONE TPU node
    launch of the right group (VERDICT item 10 'done' bar), via the mock
    GCP provider's declared node types."""
    from ray_tpu.autoscaler.demand_scheduler import get_nodes_to_launch
    from ray_tpu.autoscaler.gcp import GCPTPUNodeProvider

    cmds = []
    provider = GCPTPUNodeProvider(project="p", zone="us-central2-b",
                                  exec_fn=cmds.append)
    types = provider.node_types()

    pg = {"strategy": "STRICT_PACK",
          "bundles": [{"TPU": 4.0, "tpu-slice:v5e-8": 0.25}] * 4}
    launch = get_nodes_to_launch([], types, [{"CPU": 64.0}],
                                 pg_demands=[pg])
    # 16 TPU + slice label only fits... no single type has 16 TPU:
    # nothing launched for an unfittable strict pack
    assert launch == {}

    pg8 = {"strategy": "STRICT_PACK",
           "bundles": [{"TPU": 2.0} for _ in range(4)]}  # 8 TPU on 1 node
    launch = get_nodes_to_launch([], types, [{"CPU": 64.0}],
                                 pg_demands=[pg8])
    assert launch == {"tpu-v5e-8": 1}

    # STRICT_SPREAD: one node per bundle
    spread = {"strategy": "STRICT_SPREAD",
              "bundles": [{"TPU": 4.0}, {"TPU": 4.0}]}
    launch = get_nodes_to_launch([], types, [], pg_demands=[spread])
    assert launch in ({"tpu-v5e-4": 2},)

    # the provider creates real node records + gcloud commands
    node = provider.create_node(node_type="tpu-v5e-8")
    assert node["resources"]["TPU"] == 8.0
    assert any("tpu-vm" in c for c in cmds[0])
    assert len(provider.non_terminated_nodes()) == 1
    provider.terminate_node(node)
    assert provider.non_terminated_nodes() == []
    assert "delete" in cmds[1]


def test_demand_shape_scale_up(cluster):
    """A 4-CPU task in a 1-CPU-head cluster with free CPU present: the
    shape-blind streak heuristic could never reason about this; the
    bin-packer launches exactly one node that fits."""
    import ray_tpu as rt
    from ray_tpu.autoscaler import (Autoscaler, AutoscalerConfig,
                                    LocalNodeProvider)

    scaler = Autoscaler(
        cluster._driver.head,
        LocalNodeProvider(cluster),
        AutoscalerConfig(
            min_workers=0, max_workers=2,
            worker_resources={"CPU": 4, "memory": 2 * 2**30},
            idle_timeout_s=30.0, poll_interval_s=0.5,
        ),
    )

    @rt.remote(num_cpus=4)
    def big():
        return 99

    ref = big.remote()
    time.sleep(2.5)  # let the agent heartbeat the queued shape
    a1 = scaler.update()  # debounce poll
    a2 = scaler.update()  # launch poll
    assert a1["launched"] + a2["launched"] == 1
    assert ray_tpu.get(ref, timeout=120) == 99
    a3 = scaler.update()
    assert a3["launched"] == 0  # no double launch
