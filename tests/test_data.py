"""Dynamic returns + ray_tpu.data streaming dataset tests.

Reference analogs: python/ray/tests/test_generators.py,
python/ray/data/tests/test_dataset.py (scaled).
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_dynamic_returns_generator(cluster):
    @ray_tpu.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield np.full(4, i, dtype=np.int64)

    ref = gen.remote(5)
    out = ray_tpu.get(ref, timeout=60)
    assert isinstance(out, ray_tpu.ObjectRefGenerator)
    assert len(out) == 5
    for i, item_ref in enumerate(out):
        np.testing.assert_array_equal(
            ray_tpu.get(item_ref, timeout=60), np.full(4, i)
        )


def test_dynamic_returns_error(cluster):
    @ray_tpu.remote(num_returns="dynamic")
    def bad():
        yield 1
        raise ValueError("boom")

    ref = bad.remote()
    with pytest.raises((ValueError, ray_tpu.RayTaskError)):
        ray_tpu.get(ref, timeout=60)


def test_dataset_from_items_roundtrip(cluster):
    ds = rdata.from_items(list(range(100)), parallelism=8)
    assert ds.num_blocks() == 8
    assert ds.count() == 100
    assert sorted(r for b in ds.iter_batches() for r in b) == list(range(100))


def test_dataset_range_uses_generator_tasks(cluster):
    ds = rdata.range(64, parallelism=4)
    assert ds.num_blocks() >= 4  # each read task emitted >= 1 block
    got = np.concatenate(list(ds.iter_batches()))
    np.testing.assert_array_equal(np.sort(got), np.arange(64))


def test_map_batches_pipelined(cluster):
    ds = rdata.range(40, parallelism=4)
    doubled = ds.map_batches(lambda b: b * 2, max_in_flight=2)
    got = np.sort(np.concatenate(list(doubled.iter_batches())))
    np.testing.assert_array_equal(got, np.arange(40) * 2)


def test_filter(cluster):
    ds = rdata.from_items(list(range(20)))
    odd = ds.filter(lambda x: x % 2 == 1)
    assert sorted(odd.take(100)) == list(range(1, 20, 2))


def test_streaming_split_disjoint(cluster):
    ds = rdata.range(48, parallelism=4)
    its = ds.streaming_split(3)
    seen = []
    for it in its:
        for block in it:
            seen.extend(block.tolist())
    assert sorted(seen) == list(range(48))
    assert sum(it.num_blocks() for it in its) == ds.num_blocks()


def test_streaming_split_consumable_in_tasks(cluster):
    """DataIterators are picklable and consumable inside remote workers
    (how Train workers consume their shard)."""
    ds = rdata.from_items(list(range(30)), parallelism=6)
    its = ds.streaming_split(2)

    @ray_tpu.remote(num_cpus=1)
    def consume(it):
        total = 0
        for block in it:
            total += sum(block)
        return total

    totals = ray_tpu.get([consume.remote(it) for it in its], timeout=120)
    assert sum(totals) == sum(range(30))


def test_dataset_api_breadth_r4(cluster):
    """flat_map / map / add_column / zip / schema / stats (reference
    dataset.py surface, r4 additions)."""
    from ray_tpu import data as rdata

    ds = rdata.from_items([1, 2, 3, 4], parallelism=2)
    assert sorted(
        r for b in ds.flat_map(lambda x: [x, x * 10]).iter_batches()
        for r in b
    ) == [1, 2, 3, 4, 10, 20, 30, 40]
    assert [r for b in ds.map(lambda x: x + 1).iter_batches()
            for r in b] == [2, 3, 4, 5]

    tab = rdata.from_items([{"a": 1}, {"a": 2}], parallelism=1)
    rows = [r for b in tab.add_column("b", lambda r: r["a"] * 2)
            .iter_batches() for r in b]
    assert rows == [{"a": 1, "b": 2}, {"a": 2, "b": 4}]
    assert tab.schema() == {"a": "int"}

    z = ds.zip(ds.map(lambda x: -x))
    assert [r for b in z.iter_batches() for r in b] == [
        (1, -1), (2, -2), (3, -3), (4, -4)]

    st = ds.map_batches(lambda b: b).stats()
    assert "plan:" in st and "rows: total=4" in st, st
