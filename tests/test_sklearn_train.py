"""SklearnTrainer + BatchPredictor (reference train/sklearn/ +
batch_predictor.py test models)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import BatchPredictor, Predictor, SklearnTrainer


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.mark.slow  # ~15s; test_sklearn_fit_from_dataset below keeps tier-1 coverage
def test_sklearn_fit_and_batch_predict(cluster):
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(int)

    result = SklearnTrainer(LogisticRegression(max_iter=200), X=X, y=y).fit()
    assert result.metrics["score"] > 0.9
    est = result.checkpoint["estimator"]
    assert est.predict(X[:5]).shape == (5,)

    # dataset-parallel inference
    ds = rdata.from_numpy(X, parallelism=4)
    preds = BatchPredictor(result.checkpoint).predict(ds)
    flat = np.concatenate(preds.materialize())
    assert flat.shape == (200,)
    assert (flat == est.predict(X)).all()


def test_sklearn_fit_from_dataset(cluster):
    from sklearn.tree import DecisionTreeClassifier

    rows = [
        {"a": float(i % 7), "b": float(i % 3), "label": int(i % 2)}
        for i in range(60)
    ]
    ds = rdata.from_items(rows, parallelism=3)
    result = SklearnTrainer(
        DecisionTreeClassifier(), label_column="label",
        datasets={"train": ds},
    ).fit()
    p = Predictor.from_checkpoint(result.checkpoint)
    assert len(p.predict([[0.0, 0.0]])) == 1
