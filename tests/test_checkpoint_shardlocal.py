"""Shard-local checkpoint restore (VERDICT r2 item 7).

restore_state must read only the bytes covering the restoring process's
addressable shards (jax.make_array_from_single_device_arrays path), not
assemble full arrays host-side.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ray_tpu.parallel import MeshConfig, build_mesh
from ray_tpu.train.checkpoint import (_ShardReader, _load_device_shard,
                                      restore_state, save_state)


def _mesh8():
    return build_mesh(MeshConfig(dp=8), jax.devices()[:8])


def test_save_restore_roundtrip_sharded(tmp_path):
    mesh = _mesh8()
    sh = NamedSharding(mesh, PartitionSpec("dp", None))
    big = jax.device_put(
        jnp.arange(8 * 64 * 32, dtype=jnp.float32).reshape(8 * 64, 32), sh)
    state = {"w": big, "step": 7, "scalar": jax.device_put(
        jnp.float32(3.5), NamedSharding(mesh, PartitionSpec()))}
    save_state(state, str(tmp_path / "ck"), process_index=0)

    stats = {}
    out = restore_state(str(tmp_path / "ck"), mesh=mesh, stats=stats)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(big))
    assert out["step"] == 7
    assert float(out["scalar"]) == 3.5
    # single process addresses all 8 devices -> reads the whole array once
    # (replicated scalar read once, not 8x — the distinct-index cache)
    expected = big.nbytes + np.float32(0).nbytes
    assert stats["bytes_read"] == expected


def test_per_process_read_fraction(tmp_path):
    """Simulate process k of a multi-host mesh: loading ONE device's shard
    must touch ~1/8 of the leaf's bytes."""
    mesh = _mesh8()
    sh = NamedSharding(mesh, PartitionSpec("dp", None))
    big = jax.device_put(
        jnp.arange(8 * 64 * 32, dtype=jnp.float32).reshape(8 * 64, 32), sh)
    save_state({"w": big}, str(tmp_path / "ck"), process_index=0)

    reader = _ShardReader(str(tmp_path / "ck"))
    imap = sh.addressable_devices_indices_map(big.shape)
    one_index = next(iter(imap.values()))
    shard = _load_device_shard(reader, 0, big.shape, np.float32, one_index)
    assert shard.shape == (64, 32)
    assert reader.bytes_read == big.nbytes // 8  # exactly one shard file read
    reader.close()


def test_restore_onto_reshaped_mesh(tmp_path):
    """Saved on dp=8, restored as dp=4 x tp=2 along the other axis: the
    general overlap-assembly path must produce identical values."""
    mesh8 = _mesh8()
    sh8 = NamedSharding(mesh8, PartitionSpec("dp", None))
    big = jax.device_put(
        jnp.arange(8 * 16 * 64, dtype=jnp.float32).reshape(8 * 16, 64), sh8)
    save_state({"w": big}, str(tmp_path / "ck"), process_index=0)

    mesh42 = build_mesh(MeshConfig(dp=4, tp=2), jax.devices()[:8])
    sh42 = NamedSharding(mesh42, PartitionSpec("dp", "tp"))
    out = restore_state(str(tmp_path / "ck"), mesh=mesh42,
                        shardings={"w": sh42})
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(big))
    assert out["w"].sharding == sh42


def test_multi_writer_files_cover(tmp_path):
    """Shards written by several 'processes' (separate files) are all
    indexed; restore stitches across files."""
    mesh = _mesh8()
    sh = NamedSharding(mesh, PartitionSpec("dp"))
    v = jax.device_put(jnp.arange(64, dtype=jnp.int32), sh)
    # fake a 2-process save: write half the shards under p0, half p1
    import os

    path = str(tmp_path / "ck")
    save_state({"v": v}, path, process_index=0)
    # split the single file into two to model multi-writer layout; each
    # writer also records its own checksum file (checksums_p{k}.json),
    # so model that half of the format too
    import json
    import zlib

    z = np.load(os.path.join(path, "shards_p0.npz"))
    keys = list(z.files)
    half = len(keys) // 2
    np.savez(os.path.join(path, "shards_p0.npz"),
             **{k: z[k] for k in keys[:half]})
    np.savez(os.path.join(path, "shards_p1.npz"),
             **{k: z[k] for k in keys[half:]})
    with open(os.path.join(path, "checksums_p0.json")) as f:
        sums0 = json.load(f)
    for pid in (0, 1):
        fn = f"shards_p{pid}.npz"
        with open(os.path.join(path, fn), "rb") as f:
            sums0[fn] = zlib.crc32(f.read())
    sums1 = {"shards_p1.npz": sums0.pop("shards_p1.npz")}
    with open(os.path.join(path, "checksums_p0.json"), "w") as f:
        json.dump(sums0, f)
    with open(os.path.join(path, "checksums_p1.json"), "w") as f:
        json.dump(sums1, f)
    out = restore_state(path, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out["v"]), np.arange(64))
