"""RLModule abstraction (reference rllib/core/rl_module/rl_module.py:1)
+ APPO (reference rllib/algorithms/appo/appo.py:1): one module contract
consumed by PPO's Learner and the IMPALA/APPO machinery, a convolutional
VisionPolicyModule (visionnet analog), and APPO learning a corridor with
async sampling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.rl.rl_module import (
    DiscretePolicyModule,
    VisionPolicyModule,
)


def _fake_ppo_batch(rng, n, obs_dim, n_actions):
    return {
        "obs": rng.standard_normal((n, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, n_actions, n).astype(np.int32),
        "logp": np.full(n, -np.log(n_actions), np.float32),
        "advantages": rng.standard_normal(n).astype(np.float32),
        "returns": rng.standard_normal(n).astype(np.float32),
    }


def test_discrete_module_contract():
    mod = DiscretePolicyModule(obs_dim=5, n_actions=3)
    params = mod.init(jax.random.PRNGKey(0))
    obs = jnp.ones((7, 5))
    out = mod.forward_train(params, obs)
    assert out["logits"].shape == (7, 3)
    assert out["vf"].shape == (7,)
    act = mod.forward_inference(params, obs)
    assert act.shape == (7,)
    a, logp = mod.forward_exploration(params, obs, jax.random.PRNGKey(1))
    assert a.shape == (7,) and logp.shape == (7,)
    assert bool(jnp.all(logp <= 0.0))


def test_vision_module_forward_and_ppo_update():
    """Conv module (visionnet analog) trains through the UNCHANGED PPO
    Learner: the loss consumes only the module contract."""
    from ray_tpu.rl.learner import Learner

    h, w, c, n_actions = 12, 12, 3, 4
    mod = VisionPolicyModule((h, w, c), n_actions)
    params = mod.init(jax.random.PRNGKey(0))
    imgs = jnp.asarray(
        np.random.RandomState(0).rand(6, h, w, c), jnp.float32)
    out = mod.forward_train(params, imgs)
    assert out["logits"].shape == (6, n_actions)
    assert out["vf"].shape == (6,)

    rng = np.random.default_rng(1)
    lrn = Learner(h * w * c, n_actions, module=mod, seed=0)
    batch = _fake_ppo_batch(rng, 32, h * w * c, n_actions)
    before = jax.tree_util.tree_map(np.asarray, lrn.get_weights())
    metrics = lrn.update(batch, minibatches=2, epochs=1)
    assert np.isfinite(metrics["total_loss"])
    after = lrn.get_weights()
    moved = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        before, after)
    assert max(jax.tree_util.tree_leaves(moved)) > 0.0


def test_same_module_instance_serves_ppo_and_impala_losses():
    """The module is pure config + pure functions: ONE instance feeds
    both the PPO Learner's jitted loss and an IMPALA-style [T, N]
    forward without adapters."""
    from ray_tpu.rl.learner import Learner

    mod = DiscretePolicyModule(obs_dim=4, n_actions=2)
    lrn = Learner(4, 2, module=mod, seed=0)
    rng = np.random.default_rng(0)
    metrics = lrn.update(_fake_ppo_batch(rng, 16, 4, 2),
                         minibatches=2, epochs=1)
    assert np.isfinite(metrics["total_loss"])
    # IMPALA-style flattened [T*N, D] forward on the same instance
    out = mod.forward_train(lrn.params, jnp.ones((8 * 3, 4)))
    assert out["logits"].shape == (24, 2)


class Corridor:
    """Walk right to the end; identical to test_rl_impala's env."""

    N = 5

    def __init__(self):
        self.pos = 0
        self.t = 0

    def reset(self):
        self.pos = 0
        self.t = 0
        return self._obs()

    def _obs(self):
        return np.array([self.pos / self.N, 1.0], np.float32)

    def step(self, action):
        self.t += 1
        self.pos = max(0, self.pos + (1 if action == 1 else -1))
        done = self.pos >= self.N or self.t >= 40
        reward = 1.0 if self.pos >= self.N else -0.05
        return self._obs(), reward, done, {}


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_appo_improves_on_corridor(cluster):
    """APPO: async PPO on the IMPALA runner machinery — clipped
    surrogate over importance-corrected advantages, target-network
    value bootstrap, sampling never blocking on learning."""
    from ray_tpu.rl.appo import APPOConfig

    algo = APPOConfig(
        env_creator=Corridor, obs_dim=2, n_actions=2,
        num_env_runners=2, num_envs_per_runner=4, rollout_steps=32,
        lr=5e-3, entropy_coeff=0.02, clip=0.3, target_update_freq=4,
    ).build()
    try:
        first = algo.train()
        for _ in range(25):
            last = algo.train()
        assert last["training_iteration"] == 26
        assert 0.0 < last["mean_ratio"] < 10.0  # IS ratios sane
        assert last["episode_return_mean"] > max(
            first["episode_return_mean"] + 0.3, 0.0), (first, last)
    finally:
        algo.stop()
