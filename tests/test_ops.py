"""Ops unit tests: norms, rope, attention (reference vs flash-interpret)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import attention_reference, rms_norm, softmax_cross_entropy
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.rope import apply_rotary, rotary_embedding


def test_rms_norm_matches_manual(rng):
    x = jax.random.normal(rng, (4, 16), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (16,), jnp.float32)
    got = rms_norm(x, w, eps=1e-6)
    want = x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-6) * np.asarray(w)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_rope_norm_preserving(rng):
    x = jax.random.normal(rng, (2, 8, 4, 32), jnp.float32)
    pos = jnp.arange(8, dtype=jnp.int32)[None, :]
    sin, cos = rotary_embedding(pos, 32)
    y = apply_rotary(x, sin, cos)
    # Rotation preserves per-pair norms.
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1),
        rtol=1e-5,
    )
    # Position 0 is identity.
    np.testing.assert_allclose(np.asarray(y[:, 0]), np.asarray(x[:, 0]), atol=1e-6)


def test_attention_reference_causality(rng):
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (1, 8, 2, 16), jnp.float32)
    k = jax.random.normal(k2, (1, 8, 2, 16), jnp.float32)
    v = jax.random.normal(k3, (1, 8, 2, 16), jnp.float32)
    out1 = attention_reference(q, k, v, causal=True)
    # Perturbing future keys/values must not change earlier outputs.
    k_mod = k.at[:, 4:].set(0.0)
    v_mod = v.at[:, 4:].set(9.0)
    out2 = attention_reference(q, k_mod, v_mod, causal=True)
    np.testing.assert_allclose(np.asarray(out1[:, :4]), np.asarray(out2[:, :4]), rtol=1e-5)
    assert not np.allclose(np.asarray(out1[:, 4:]), np.asarray(out2[:, 4:]))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("gqa", [1, 2])
def test_flash_matches_reference(rng, causal, gqa):
    b, t, hq, d = 2, 256, 4, 64
    hkv = hq // gqa
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, hq, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, hkv, d), jnp.float32)
    want = attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=1e-4)


def test_flash_gradients_match_reference(rng):
    b, t, h, d = 1, 128, 2, 32
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, h, d), jnp.float32)

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                               interpret=True).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4, rtol=1e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gradients_match_reference_gqa(rng, causal):
    b, t, hq, hkv, d = 1, 128, 4, 2, 32
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, hq, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, hkv, d), jnp.float32)

    def f_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) ** 2).sum()

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                                interpret=True) ** 2).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-4, rtol=1e-3)


def test_flash_gradients_decode_shape(rng):
    """T != S gradients (end-aligned causal mask in the backward)."""
    b, t, s, h, d = 1, 64, 128, 2, 32
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, s, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, s, h, d), jnp.float32)

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                               interpret=True).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4, rtol=1e-3)


def test_flash_empty_rows_t_gt_s(rng):
    """T > S causal: leading rows attend nothing -> output 0, gradients 0,
    including rows straddling a live block."""
    b, t, s, h, d = 1, 256, 128, 2, 32
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, s, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, s, h, d), jnp.float32)
    want = attention_reference(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    # offset = -128: rows 0..127 attend nothing (blocks 0..1 of 64 are dead).
    np.testing.assert_allclose(np.asarray(got[:, :128]), 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got[:, 128:]),
                               np.asarray(want[:, 128:]), atol=2e-5, rtol=1e-4)

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                               interpret=True).sum()

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4,
                                   rtol=1e-3)


def test_flash_gradients_non_pow2_seq(rng):
    """Seq len where naive bwd tile widening would go ragged (1536 % 1024)."""
    b, t, h, d = 1, 1536, 1, 32
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, h, d), jnp.float32)

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=512, block_k=512,
                               interpret=True).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-4,
                                   rtol=1e-3)


def test_flash_decode_shape_matches_reference(rng):
    """T != S (decode against a cache): mask must be end-aligned."""
    b, t, s, h, d = 1, 64, 256, 2, 32
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, s, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, s, h, d), jnp.float32)
    want = attention_reference(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5,
                               rtol=1e-4)


def test_flash_rejects_ragged_lengths(rng):
    q = jnp.zeros((1, 100, 2, 32))
    with pytest.raises(ValueError, match="multiples"):
        flash_attention(q, q, q, causal=True, block_q=64, block_k=64,
                        interpret=True)


def test_cross_entropy_uniform(rng):
    logits = jnp.zeros((4, 7, 10))
    labels = jnp.zeros((4, 7), jnp.int32)
    loss, n = softmax_cross_entropy(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(10), rtol=1e-6)
    assert int(n) == 28


def test_flash_long_seq_multiblock_fwd(rng):
    """S > _FULL_INNER_MAX forces the tiled online-softmax forward kernel
    (log2-domain running max/corr) — unreachable at short S, where the
    single-pass kernel runs instead."""
    b, t, h, d = 1, 4096, 1, 32
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, h, d), jnp.float32)
    want = attention_reference(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=1024, block_k=1024,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=1024,
                               block_k=1024, interpret=True).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=1e-3)


def test_flash_legacy_bwd_path_very_long_kv(rng):
    """S large enough that the fused backward's dq-partial array is
    ineligible (> _MAX_DQ_PARTIALS) — exercises the legacy two-kernel
    backward, which otherwise has no reachable configuration."""
    from ray_tpu.ops.flash_attention import _fused_blocks

    b, t, s, h, d = 1, 256, 16384, 1, 32
    assert _fused_blocks(t, s, 256, 1024) is None  # really the legacy path
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, s, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, s, h, d), jnp.float32)

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=256,
                               block_k=1024, interpret=True).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=1e-3)


@pytest.mark.parametrize("hb", [2, 4])
def test_flash_heads_per_block_matches_reference(rng, hb):
    """flash_heads_per_block > 1 (multi-head grid cells, MHA only) must
    be numerically identical to the per-head layout."""
    from ray_tpu._private import config as _cfg

    b, t, h, d = 2, 256, 4, 64
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, h, d), jnp.float32)
    want = attention_reference(q, k, v, causal=True)
    old = _cfg.get("flash_heads_per_block")
    try:
        _cfg.set_system_config({"flash_heads_per_block": hb})
        got = flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=256, interpret=True)
    finally:
        _cfg.set_system_config({"flash_heads_per_block": old})
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("hb", [2, 4])
def test_flash_bwd_heads_per_block_matches_reference(rng, hb):
    """flash_bwd_heads_per_block > 1 (multi-head fused-backward cells,
    MHA only) must produce the same gradients as the per-head layout."""
    from ray_tpu._private import config as _cfg

    b, t, h, d = 2, 512, 4, 64
    k1, k2, k3 = jax.random.split(rng, 3)
    q = jax.random.normal(k1, (b, t, h, d), jnp.float32)
    k = jax.random.normal(k2, (b, t, h, d), jnp.float32)
    v = jax.random.normal(k3, (b, t, h, d), jnp.float32)

    def f_ref(q, k, v):
        return attention_reference(q, k, v, causal=True).sum()

    def f_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, block_q=256,
                               block_k=512, interpret=True).sum()

    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    old = _cfg.get("flash_bwd_heads_per_block")
    try:
        _cfg.set_system_config({"flash_bwd_heads_per_block": hb})
        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    finally:
        _cfg.set_system_config({"flash_bwd_heads_per_block": old})
    for a, b_ in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=1e-3)
