"""Tune v0 tests: random/grid search, ASHA early stopping, best-trial
checkpoint restore. Reference analogs: python/ray/tune/tests/test_tune_*.py
(scaled) per VERDICT round-1 item 10.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import Checkpoint


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_random_search_finds_good_lr(cluster):
    """Quadratic bowl: trials with lr nearer 0.3 score better."""

    def trainable(config):
        lr = config["lr"]
        loss = (lr - 0.3) ** 2
        for _ in range(3):
            tune.report({"loss": loss})

    tuner = tune.Tuner(
        trainable,
        param_space={"lr": tune.loguniform(1e-3, 1.0)},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=8,
            max_concurrent_trials=4, seed=7,
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 8
    best = grid.get_best_result()
    assert best.metrics["loss"] == min(
        r.metrics["loss"] for r in grid if r.metrics
    )


def test_grid_search_runs_every_value(cluster):
    def trainable(config):
        tune.report({"loss": config["x"] ** 2, "x": config["x"]})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([-2, -1, 0, 1, 2])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    num_samples=1),
    )
    grid = tuner.fit()
    assert sorted(r.metrics["x"] for r in grid) == [-2, -1, 0, 1, 2]
    assert grid.get_best_result().metrics["x"] == 0


def test_asha_stops_bad_trials_early(cluster):
    """Bad trials (high loss) must be stopped before max_t reports."""

    def trainable(config):
        for step in range(20):
            tune.report({"loss": config["level"] + step * 0.0})

    tuner = tune.Tuner(
        trainable,
        param_space={"level": tune.grid_search(
            [0.1, 0.2, 5.0, 6.0, 7.0, 8.0]
        )},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=1,
            max_concurrent_trials=6,
            scheduler=tune.ASHAScheduler(
                max_t=20, grace_period=2, reduction_factor=2,
            ),
        ),
    )
    grid = tuner.fit()
    iters = {r.config["level"]: r.metrics["training_iteration"]
             for r in grid if r.metrics}
    # the best trial survived longer than the worst
    assert iters[0.1] > min(iters[5.0], iters[6.0], iters[7.0], iters[8.0])
    best = grid.get_best_result()
    assert best.config["level"] in (0.1, 0.2)


@pytest.mark.slow  # ~22s; tune surface covered by the grid tests above
def test_tune_tiny_llama_lr_with_checkpoints(cluster, tmp_path):
    """VERDICT item 10 'done' bar: tune tiny-llama LR over trials; best
    trial's checkpoint is restorable."""

    def trainable(config):
        import os

        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu import tune as T
        from ray_tpu.models import llama
        from ray_tpu.train import checkpoint as ckpt_mod

        jax.config.update("jax_platforms", "cpu")
        cfg = llama.LlamaConfig.tiny()
        params = llama.init_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adam(config["lr"])
        opt_state = opt.init(params)
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, 33), 0, cfg.vocab_size
        )
        batch = {"tokens": toks}

        @jax.jit
        def step(params, opt_state):
            (loss, _), grads = jax.value_and_grad(
                lambda p: llama.loss_fn(p, batch, cfg), has_aux=True
            )(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        for i in range(4):
            params, opt_state, loss = step(params, opt_state)
            path = os.path.join(
                config["storage"], f"lr{config['lr']:.6f}_step{i}"
            )
            ck = ckpt_mod.save_state(
                {"params": params}, path, process_index=0,
                extra={"loss": float(loss), "step": i + 1},
            )
            T.report({"loss": float(loss)}, checkpoint=ck)

    tuner = tune.Tuner(
        trainable,
        param_space={
            "lr": tune.grid_search([1e-4, 1e-3, 1e-2, 3e-2]),
            "storage": str(tmp_path),
        },
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", num_samples=1,
            max_concurrent_trials=4,
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.checkpoint is not None
    # restore the winning checkpoint on the driver's single-device "mesh"
    import jax
    from jax.sharding import Mesh

    from ray_tpu.train import restore_state

    mesh = Mesh(np.array(jax.devices()[:1]), ("d",))
    state = restore_state(best.checkpoint.path, mesh=mesh)
    assert "params" in state and "embed" in state["params"]
    meta = Checkpoint(best.checkpoint.path).to_dict()
    assert meta["step"] == 4
