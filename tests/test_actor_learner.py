"""Actor–learner loop (ISSUE 12): RLHF post-training on the serving
pool + elastic DCN learners.

Covers the acceptance criteria end to end:

- a small llama policy trained by `ActorLearnerLoop` improves mean
  reward over its frozen init on a synthetic reward, deterministically
  under fixed seeds (sync mode: bit-identical reward curves);
- a mid-run decode-replica kill and a learner-rank kill each recover
  with ZERO gang restarts (in-place resume) and no lost or duplicated
  trajectories (buffer conservation + unique consumption);
- weight-version staleness: replicas adopt a published version within K
  engine steps; trajectories carry their generating version; the
  learner's importance correction is exercised by an off-by-one-version
  fixture;
- the randomized chaos soak extends to the serving pool + RL loop
  (profile="rl" fault plans; 1-seed smoke in tier-1, sweep in `slow`).
"""

import json
import sys
import threading
import time

import cloudpickle
import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import config as _cfg
from ray_tpu._private import fault_injection as fi
from ray_tpu._private.chaos import gen_fault_plan
from ray_tpu.cluster_utils import Cluster
from ray_tpu.rl.experience import ExperienceBuffer

# worker subprocesses can't import the tests package: ship by value
cloudpickle.register_pickle_by_value(sys.modules[__name__])


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()
    _cfg.set_system_config({"fault_spec": ""})


# ---------------- ExperienceBuffer units (no cluster) ----------------


def _mk(buffer, n, version=0, key0=0):
    return [buffer.add({"key": (0, key0 + i), "version": version,
                        "traj": {"n": key0 + i}})
            for i in range(n)]


def test_buffer_fifo_claims_and_dedup():
    b = ExperienceBuffer()
    out = _mk(b, 5)
    assert [o["seq"] for o in out] == [0, 1, 2, 3, 4]
    # duplicate key rejected, original seq reported
    dup = b.add({"key": (0, 2), "version": 0, "traj": {}})
    assert not dup["accepted"] and dup["seq"] == 2
    c1 = b.claim("rank0", 2, iteration=1)
    c2 = b.claim("rank1", 2, iteration=1)
    assert [e["seq"] for e in c1["entries"]] == [0, 1]
    assert [e["seq"] for e in c2["entries"]] == [2, 3]
    # partial claim drains what's left; empty poll has no claim id
    c3 = b.claim("rank0", 5, iteration=2)
    assert [e["seq"] for e in c3["entries"]] == [4]
    assert b.claim("rank0", 1, iteration=2)["claim_id"] is None
    st = b.stats()
    assert st["added"] == 5 and st["dups"] == 1
    assert st["consumed"] == 5 and st["queued"] == 0


def test_buffer_rollback_is_exact():
    """Claims from OLD incarnations past the restored iteration reopen
    (front of queue, in order); ones inside the checkpoint stay
    consumed; the CURRENT incarnation's claims are never touched."""
    b = ExperienceBuffer()
    _mk(b, 6)
    b.claim("rank0", 2, iteration=1, incarnation=0)   # inside ckpt
    c2 = b.claim("rank0", 2, iteration=2, incarnation=0)  # lost update
    # a fast-resumed peer already claimed at the NEW incarnation
    c3 = b.claim("rank1", 2, iteration=2, incarnation=1)
    out = b.rollback(restored_iteration=1, incarnation=1)
    assert out["reopened"] == 2
    st = b.stats()
    assert st["queued"] == 2  # c2's seqs back in the queue
    assert sorted(st["consumed_seqs"]) == [0, 1, 4, 5]
    # reopened seqs come back FIRST and in order
    re = b.claim("rank0", 4, iteration=2, incarnation=1)
    assert [e["seq"] for e in re["entries"]] == [e["seq"]
                                                for e in c2["entries"]]
    # conservation + uniqueness all the way through, and the NEW
    # incarnation's claim survived the rollback untouched
    st = b.stats()
    assert st["added"] == st["queued"] + st["consumed"] \
        + st["dropped_stale"]
    assert len(set(st["consumed_seqs"])) == st["consumed"]
    assert {e["seq"] for e in c3["entries"]} <= set(st["consumed_seqs"])


def test_buffer_finalize_frees_consumed_payloads():
    """finalize_through unpins the trajectory payloads of claims whose
    update is durably checkpointed (bounds store growth over a long
    run) while the conservation accounting keeps holding; a rollback
    that somehow reaches past the finalize horizon counts the freed
    claims as unrecoverable instead of silently losing them."""
    b = ExperienceBuffer()
    _mk(b, 6)
    b.claim("rank0", 2, iteration=1)        # -> finalized
    b.claim("rank0", 2, iteration=5)        # recent: stays pinned
    out = b.finalize_through(3)
    assert out["freed"] == 2
    st = b.stats()
    assert st["pinned"] == 4  # 2 queued + 2 recent-claimed
    assert st["consumed"] == 4  # accounting unchanged by the free
    assert st["added"] == st["queued"] + st["consumed"] \
        + st["dropped_stale"]
    # double finalize is a no-op
    assert b.finalize_through(3)["freed"] == 0
    # a rollback past the horizon cannot re-deliver freed claims
    out = b.rollback(restored_iteration=0, incarnation=1)
    assert out["unrecoverable"] == 2
    assert out["reopened"] == 2  # the iteration-5 claim came back


def test_buffer_staleness_eviction_and_rejection():
    b = ExperienceBuffer(max_version_lag=1)
    _mk(b, 3, version=0)
    _mk(b, 2, version=2, key0=10)
    out = b.set_version(2)  # window [1, 2]: v0 entries evicted
    assert out["dropped"] == 3
    assert b.size() == 2
    rej = b.add({"key": (9, 9), "version": 0, "traj": {}})
    assert not rej["accepted"]
    st = b.stats()
    assert st["dropped_stale"] == 3 and st["rejected_stale"] == 1
    assert st["added"] == st["queued"] + st["consumed"] \
        + st["dropped_stale"]


# ------------- off-by-one-version importance correction -------------


def _np_vtrace(beh, tgt, r, gamma):
    """Direct numpy transcription of the rl/vtrace.py recursion
    (values = 0, bootstrap = 0, rho_bar = c_bar = lam = 1, no dones,
    single trajectory): vs_t = delta_t + gamma * c_t * vs_{t+1} with
    delta_t = rho_t * (r_t + gamma * vs'_{t+1}) where vs' is V (= 0)."""
    t_len = len(r)
    rho = np.minimum(1.0, np.exp(tgt - beh))
    c = np.minimum(1.0, np.exp(tgt - beh))
    # err_t = vs_t - V_t; with V = 0 and next_values = 0:
    err = np.zeros(t_len + 1, np.float64)
    for t in reversed(range(t_len)):
        delta = rho[t] * (r[t] + gamma * 0.0 - 0.0)
        err[t] = delta + gamma * c[t] * err[t + 1]
    vs = err[:t_len]
    next_vs = np.concatenate([vs[1:], [0.0]])
    adv = rho * (r + gamma * next_vs)
    return vs, adv


def test_off_by_one_version_importance_correction():
    """A trajectory sampled under v0 weights, corrected against v1
    weights one publish later: ratios move off 1, V-trace clips them,
    and the jax path matches a numpy transcription of the recursion."""
    jax = pytest.importorskip("jax")
    import functools

    import jax.numpy as jnp

    from ray_tpu.models.decode_engine import RaggedDecoder
    from ray_tpu.rl.actor_learner import _pg_loss, _stack_batch
    from ray_tpu.rl.vtrace import vtrace
    from ray_tpu.serve.llm import build_model

    params0, cfg = build_model("tiny", max_len=64, seed=0)
    # v1 = one synthetic update later (deterministic perturbation)
    params1 = jax.tree_util.tree_map(
        lambda a: a * 1.05 if a.ndim >= 2 else a, params0)

    eng = RaggedDecoder(params0, cfg, slots=2, max_len=64,
                        chunk_tokens=4, prompt_buckets=(8,))
    rng = np.random.RandomState(11)
    prompt = rng.randint(1, 250, 8).astype(np.int32)
    sid = eng.submit(prompt, 8, temperature=1.0, seed=21)
    eng.drain()
    s = eng.pop_finished(sid)
    traj = {"prompt": prompt, "tokens": np.asarray(s.tokens[:8], np.int32),
            "logprobs": np.asarray(s.logprobs[:8], np.float32),
            "rewards": rng.rand(8).astype(np.float32)}
    batch = {k: jnp.asarray(v)
             for k, v in _stack_batch([traj], 8, 8).items()}

    loss_fn = functools.partial(
        _pg_loss, cfg=cfg, gamma=0.9, rho_bar=1.0, c_bar=1.0,
        clip_eps=0.3, temperature=1.0, entropy_coeff=0.0)
    _, aux_same = loss_fn(params0, batch, jnp.float32(0.0))
    _, aux_off = loss_fn(params1, batch, jnp.float32(0.0))
    # same version: exactly on-policy; one version later: corrected
    assert abs(float(aux_same["mean_ratio"]) - 1.0) < 1e-4
    assert abs(float(aux_off["mean_ratio"]) - 1.0) > 1e-3

    # the vtrace recursion itself vs numpy, with genuinely off ratios
    beh = traj["logprobs"].astype(np.float64)
    tgt = beh + rng.uniform(-1.0, 0.5, 8)
    r = rng.standard_normal(8)
    vs_ref, adv_ref = _np_vtrace(beh, tgt, r, gamma=0.9)
    vs, adv = vtrace(beh, tgt, r, np.zeros(8), 0.0, np.zeros(8),
                     gamma=0.9, rho_bar=1.0, c_bar=1.0)
    # dones=0 here: bootstrap 0 still cuts at the end because vs[T]=0
    np.testing.assert_allclose(np.asarray(vs), vs_ref, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(adv), adv_ref, rtol=1e-5)


# ---------------- cluster-backed end-to-end ----------------


def _loop_config(**kw):
    from ray_tpu.rl.actor_learner import ActorLearnerConfig

    base = dict(prompt_len=8, max_new=8, iterations=6,
                trajectories_per_iter=8, n_rollout_actors=1,
                num_learners=1, lr=4.0, publish_every=1, base_seed=1)
    base.update(kw)
    return ActorLearnerConfig(**base)


def _pool_kwargs(**kw):
    base = dict(slots=4, chunk_tokens=4, min_replicas=1, max_replicas=1,
                autoscale=False)
    base.update(kw)
    return base


def _assert_exact_delivery(buffer_stats):
    """The 'no lost or duplicated trajectories' criterion: every added
    trajectory is queued, consumed by exactly one surviving claim, or
    evicted by the staleness window — and nothing is consumed twice."""
    st = buffer_stats
    assert st["added"] == st["queued"] + st["consumed"] \
        + st["dropped_stale"], st
    assert len(set(st["consumed_seqs"])) == st["consumed"], st


def test_weight_version_staleness_bounded(cluster):
    """Replicas adopt a published version within K engine steps, late
    spawns adopt the latest ref, and streams carry their generating
    version."""
    from ray_tpu.serve.llm import build_model
    from ray_tpu.serve.llm_pool import LLMPool

    import jax

    K = 100  # engine pump ticks (idle ticks are ~5ms): adoption is one
    # chunk boundary + RPC, far inside this
    pool = LLMPool(model_size="tiny", slots=2, max_len=96,
                   chunk_tokens=4, prompt_buckets=(8,),
                   min_replicas=2, max_replicas=2, autoscale=False)
    try:
        before = {r.name: ray_tpu.get(r.handle.stats.remote(),
                                      timeout=60)["pumps"]
                  for r in pool._alive()}
        params, _ = build_model("tiny", max_len=96, seed=3)
        host = jax.tree_util.tree_map(np.asarray, params)
        v = pool.publish_weights(host)
        assert v == 1
        assert pool.wait_version(v, timeout=60.0)
        for r in pool._alive():
            st = ray_tpu.get(r.handle.stats.remote(), timeout=60)
            assert st["weights_version"] == 1
            assert st["pumps"] - before[r.name] <= K, (
                f"{r.name} took {st['pumps'] - before[r.name]} steps")
        # fresh requests are stamped with the generating version
        out = pool.generate([1, 2, 3, 4], 4)
        assert out["weights_version"] == 1
        sub = pool.submit_stream({"prompt_ids": [1, 2, 3, 4],
                                  "max_tokens": 4})
        assert sub["weights_version"] == 1
        # ... and stream polls pin to the ENGINE's admission version
        # (the generating version, not merely the publish stamp)
        toks = []
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            p = pool.poll_stream(sub["rid"])
            toks.extend(p["tokens"])
            assert p["weights_version"] == 1
            if p["done"]:
                break
            time.sleep(0.01)
        assert toks, "stream produced no tokens"
    finally:
        pool.shutdown()


@pytest.mark.slow  # ~39s e2e; reward improvement covered by test_rl.py PPO corridor
def test_e2e_improves_reward_deterministically(cluster):
    """THE acceptance run: frozen init → trained policy improves mean
    reward on the synthetic reward; sync mode makes the whole loop
    bit-deterministic under fixed seeds (two runs, identical curves)."""
    from ray_tpu.rl.actor_learner import ActorLearnerLoop

    def one_run():
        loop = ActorLearnerLoop(
            _loop_config(sync_mode=True),
            pool_kwargs=_pool_kwargs())
        try:
            out = loop.run()
        finally:
            loop.shutdown()
        assert out["error"] is None, out["error"]
        assert out["resumes"] == {"inplace": 0, "gang": 0}
        _assert_exact_delivery(out["buffer"])
        return out

    a = one_run()
    assert len(a["rewards"]) == 6
    # improvement over the frozen init's on-policy reward
    assert a["rewards"][-1] >= a["rewards"][0] + 0.2, a["rewards"]
    assert a["rewards"][-1] >= 0.85, a["rewards"]
    assert a["publishes"] == 6 and a["final_version"] == 6
    assert a["adoption_latency_s"] is not None

    b = one_run()
    assert a["rewards"] == b["rewards"], (a["rewards"], b["rewards"])


@pytest.mark.slow  # ~40s chaos soak; faster kill coverage: pool replica-kill tests + test_soak_smoke
def test_chaos_replica_and_learner_kill_recover_inplace(cluster):
    """Mid-run decode-replica kill AND learner-rank kill: the loop
    finishes with zero gang restarts (the learner death resumes
    in-place; the replica death fails over inside the pool) and exact
    trajectory delivery."""
    from ray_tpu.rl.actor_learner import ActorLearnerLoop

    cfg = _loop_config(
        iterations=8, n_rollout_actors=2, num_learners=2,
        base_seed=3, max_failures=0, max_inplace_resumes=8,
        # learner rank 1 hard-dies mid-allreduce a couple of
        # iterations in
        worker_specs=[{"site": "ring.send", "action": "exit",
                       "match": {"rank": 1}, "after": 6, "count": 1}])
    loop = ActorLearnerLoop(
        cfg, pool_kwargs=_pool_kwargs(min_replicas=2, max_replicas=2))

    killed = {}

    def kill_replica():
        time.sleep(3.0)
        victims = loop.pool._alive()
        if victims:
            ray_tpu.kill(victims[0].handle)
            killed["name"] = victims[0].name

    th = threading.Thread(target=kill_replica, daemon=True)
    th.start()
    try:
        out = loop.run()
    finally:
        loop.shutdown()
    th.join(timeout=10)

    assert killed.get("name"), "replica kill never fired"
    assert out["error"] is None, out["error"]
    assert out["resumes"]["gang"] == 0, out["resumes"]
    assert out["resumes"]["inplace"] >= 1, out["resumes"]
    assert len(out["rewards"]) == 8
    _assert_exact_delivery(out["buffer"])
    # the lost iteration's claims were re-delivered, not dropped
    assert out["buffer"]["reopened"] >= 1
    # every rollout became exactly one trajectory (failover hid the
    # replica death from the experience path)
    assert out["rollouts"]["trajectories"] == out["buffer"]["added"] \
        + out["buffer"]["rejected_stale"] + out["buffer"]["dups"]


# ---------------- randomized RL chaos soak ----------------

RL_SMOKE_SEEDS = (7,)   # serve.replica_pump exit + checkpoint noise
RL_SOAK_SEEDS = tuple(range(70, 78))
RL_DEADLINE_S = 180.0


def _run_rl_seed(cluster, seed: int, deadline_s: float):
    from ray_tpu.rl.actor_learner import ActorLearnerLoop

    plan = gen_fault_plan(seed, profile="rl", world_size=2,
                          max_faults=2, n_replicas=2, n_rollout=2)
    fi.clear()
    if plan.driver_specs:
        fi.configure(plan.driver_specs)
    # serve-pool actors arm via the env-propagated spec; set it BEFORE
    # the pool spawns its replicas
    _cfg.set_system_config({
        "fault_spec": json.dumps(plan.serve_specs)
        if plan.serve_specs else ""})
    cfg = _loop_config(
        iterations=6, n_rollout_actors=2, num_learners=2,
        base_seed=seed, max_failures=1, max_inplace_resumes=8,
        worker_specs=plan.worker_specs)
    loop = ActorLearnerLoop(
        cfg, pool_kwargs=_pool_kwargs(min_replicas=2, max_replicas=2,
                                      autoscale=True))
    t0 = time.monotonic()
    try:
        out = loop.run()
        elapsed = time.monotonic() - t0
        assert out["error"] is None, out["error"]
        assert len(out["rewards"]) == 6
        # every covered fault recovers without a gang restart
        assert out["resumes"]["gang"] == 0, out["resumes"]
        _assert_exact_delivery(out["buffer"])
        assert elapsed < deadline_s, (
            f"seed {seed} converged but took {elapsed:.1f}s: "
            f"{plan.describe()}")
        return out, elapsed
    except BaseException:
        print(f"\nRL CHAOS FAILURE {plan.describe()}\n"
              f"replay: RAY_TPU_FAULT_SPEC='{plan.env_value()}'\n",
              file=sys.stderr, flush=True)
        raise
    finally:
        loop.shutdown()
        fi.clear()
        _cfg.set_system_config({"fault_spec": ""})


@pytest.mark.slow  # ~36s soak; tier-1 keeps off-by-one + staleness e2e above
def test_rl_soak_smoke(cluster):
    """Tier-1: one fixed rl-profile seed (decode-replica death) under a
    hard deadline."""
    for seed in RL_SMOKE_SEEDS:
        out, elapsed = _run_rl_seed(cluster, seed, RL_DEADLINE_S)
        print(f"rl smoke seed {seed}: {elapsed:.1f}s "
              f"resumes={out['resumes']}")


@pytest.mark.slow
def test_rl_soak_randomized(cluster):
    """The sweep: randomized rl-profile seeds over the pool + learner
    fault surface; every one must finish with exact delivery."""
    report = []
    for seed in RL_SOAK_SEEDS:
        out, elapsed = _run_rl_seed(cluster, seed, RL_DEADLINE_S)
        report.append((seed, round(elapsed, 1), out["resumes"]))
    print("\nrl soak report (seed, seconds, resumes):")
    for row in report:
        print(f"  {row}")
    assert len(report) == len(RL_SOAK_SEEDS)
