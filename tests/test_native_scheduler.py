"""Native (C++) cluster-resource scheduler core.

Pins the semantics the node agent delegates to _native/scheduler.cc
(reference analog: src/ray/raylet/scheduling/cluster_resource_scheduler.h
+ policy/hybrid_scheduling_policy.h): fixed-point accounting (no float
drift), hybrid local-preference, top-k seeded tie-breaks, spread mode,
and the placed / queue / infeasible status triage.
"""

import pytest

from ray_tpu._native.scheduler import (
    NativeScheduler,
    PICK_INFEASIBLE,
    PICK_PLACED,
    PICK_QUEUE,
)


@pytest.fixture()
def sched():
    s = NativeScheduler()
    s.upsert_node("aa", {"CPU": 4}, {"CPU": 4})
    s.upsert_node("bb", {"CPU": 8, "TPU": 4}, {"CPU": 8, "TPU": 4})
    return s


def test_local_preference_under_threshold(sched):
    status, node = sched.pick({"CPU": 1}, local_node_id="aa")
    assert (status, node) == (PICK_PLACED, "aa")


def test_spills_when_local_would_saturate(sched):
    # 4 CPUs on an idle 4-CPU node = utilization 1.0 > threshold; the
    # idle 8-CPU peer scores lower and wins.
    status, node = sched.pick({"CPU": 4}, local_node_id="aa", threshold=0.75)
    assert (status, node) == (PICK_PLACED, "bb")


def test_resource_type_routing(sched):
    status, node = sched.pick({"TPU": 2}, local_node_id="aa")
    assert (status, node) == (PICK_PLACED, "bb")


def test_infeasible(sched):
    status, node = sched.pick({"GPU": 1}, local_node_id="aa")
    assert status == PICK_INFEASIBLE and node is None


def test_queue_when_busy_everywhere(sched):
    assert sched.acquire("bb", {"CPU": 8})
    status, node = sched.pick({"CPU": 6}, local_node_id="aa")
    assert status == PICK_QUEUE and node == "bb"


def test_dead_nodes_excluded(sched):
    sched.upsert_node("bb", {"CPU": 8}, {"CPU": 8}, alive=False)
    status, _ = sched.pick({"CPU": 6}, local_node_id="aa")
    assert status == PICK_INFEASIBLE


def test_acquire_release_roundtrip(sched):
    assert sched.acquire("aa", {"CPU": 3})
    assert sched.available("aa", "CPU") == 1.0
    assert not sched.acquire("aa", {"CPU": 2})
    sched.release("aa", {"CPU": 3})
    assert sched.available("aa", "CPU") == 4.0


def test_fixed_point_no_drift(sched):
    for _ in range(10_000):
        assert sched.acquire("aa", {"CPU": 0.1})
        sched.release("aa", {"CPU": 0.1})
    assert sched.available("aa", "CPU") == 4.0


def test_release_clamped_to_total(sched):
    sched.release("aa", {"CPU": 99})
    assert sched.available("aa", "CPU") == 4.0


def test_top_k_seeded_and_bounded():
    s = NativeScheduler()
    for i in range(8):
        s.upsert_node(f"n{i}", {"CPU": 4}, {"CPU": 4})
    picks = {s.pick({"CPU": 1}, seed=seed, top_k=3)[1] for seed in range(64)}
    # ids sort lexicographically; equal scores -> only the first k eligible
    assert picks <= {"n0", "n1", "n2"}
    assert len(picks) > 1  # the seed actually varies the choice
    # deterministic for a fixed seed
    assert all(
        s.pick({"CPU": 1}, seed=7)[1] == s.pick({"CPU": 1}, seed=7)[1]
        for _ in range(5)
    )


def test_spread_ignores_local_preference():
    s = NativeScheduler()
    s.upsert_node("aa", {"CPU": 4}, {"CPU": 2})  # local, half used
    s.upsert_node("bb", {"CPU": 4}, {"CPU": 4})  # idle peer
    status, node = s.pick(
        {"CPU": 1}, local_node_id="aa", spread=True, top_k=1
    )
    assert (status, node) == (PICK_PLACED, "bb")


def test_remove_node():
    s = NativeScheduler()
    s.upsert_node("aa", {"CPU": 4}, {"CPU": 4})
    s.upsert_node("bb", {"CPU": 4}, {"CPU": 4})
    s.remove_node("bb")
    assert s.num_nodes() == 1
    assert s.pick({"CPU": 1})[1] == "aa"
