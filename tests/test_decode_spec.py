"""Speculative decoding: draft/verify in one engine step (ISSUE 19).

The contract under test: speculation is a LATENCY optimization, never a
distribution change —

- greedy (temperature 0) through the spec kernel is BIT-IDENTICAL to
  the non-speculative path (accept-until-mismatch against the target's
  own argmax token reconstructs exactly the plain sequence);
- sampled streams are a pure function of (weights, prompt, seed)
  REGARDLESS of spec depth, because acceptance is judged against the
  target's own (seed, position) RNG-lane token — the same token the
  plain kernel would emit.  That is what keeps failover seed-replay
  exact with speculation enabled;
- the `serve.spec_verify` chaos site degrades a "drop" pump to the
  plain kernel (retryable by construction: same tokens either way);
- serve_spec_enabled / serve_spec_depth flip speculation live, per
  pump, without rebuilding the engine;
- the zero-init draft head is an exact identity at init.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu._private import config as _cfg  # noqa: E402
from ray_tpu._private import fault_injection as fi  # noqa: E402
from ray_tpu.models import llama, mlp  # noqa: E402
from ray_tpu.models.decode_engine import RaggedDecoder  # noqa: E402

TINY = llama.LlamaConfig(
    vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
    d_ff=128, max_seq_len=64, dtype="float32", remat=False)


@pytest.fixture(scope="module")
def params():
    return llama.init_params(TINY, jax.random.PRNGKey(0))


def _run(params, prompt, n, *, temperature, seed, spec_depth=0,
         draft_layers=1, draft_head=None, extra_streams=0, slots=4,
         chunk=4, rng_seed=99):
    """Decode one stream (optionally amid unrelated concurrent sampled
    streams) and return (tokens, logprobs, engine-stats)."""
    eng = RaggedDecoder(params, TINY, slots=slots, max_len=64,
                        chunk_tokens=chunk, prompt_buckets=(8, 16),
                        spec_depth=spec_depth,
                        spec_draft_layers=draft_layers,
                        spec_draft_head=draft_head)
    rng = np.random.RandomState(rng_seed)
    others = [eng.submit(rng.randint(1, 250, 6).astype(np.int32), n,
                         temperature=0.7, seed=int(rng.randint(2**31)))
              for _ in range(extra_streams)]
    sid = eng.submit(np.asarray(prompt, np.int32), n,
                     temperature=temperature, seed=seed)
    eng.drain()
    s = eng.pop_finished(sid)
    for o in others:
        eng.purge(o)
    return (np.asarray(s.tokens[:n]),
            np.asarray(s.logprobs[:n], np.float32), eng.stats())


@pytest.mark.parametrize("depth", [2, 4])
def test_spec_greedy_bit_identical_to_plain(params, depth):
    """Temperature 0 must reproduce the plain engine's tokens exactly —
    rejected drafts roll back by truncating the slot's cache length,
    and the verify's own argmax fills the first mismatch, so no
    speculative state ever leaks into output."""
    rng = np.random.RandomState(1)
    prompt = rng.randint(1, 250, 7).astype(np.int32)
    base, base_lp, _ = _run(params, prompt, 16, temperature=0.0, seed=5)
    toks, lps, st = _run(params, prompt, 16, temperature=0.0,
                         seed=5, spec_depth=depth)
    np.testing.assert_array_equal(toks, base)
    np.testing.assert_array_equal(lps, base_lp)
    assert st["spec"]["pumps"] > 0


def test_spec_sampled_seed_replay_across_depths(params):
    """The failover contract with speculation ON: one (prompt, seed)
    yields identical tokens whether decoded plain, at depth 2, at
    depth 4, or at depth 4 amid unrelated concurrent streams.  The
    accepted-draft prefix length varies run to run; the emitted
    sequence must not."""
    rng = np.random.RandomState(2)
    prompt = rng.randint(1, 250, 7).astype(np.int32)
    base, base_lp, _ = _run(params, prompt, 14, temperature=0.9,
                            seed=777)
    d2, d2_lp, _ = _run(params, prompt, 14, temperature=0.9, seed=777,
                        spec_depth=2)
    d4, d4_lp, st = _run(params, prompt, 14, temperature=0.9, seed=777,
                         spec_depth=4)
    crowd, crowd_lp, _ = _run(params, prompt, 14, temperature=0.9,
                              seed=777, spec_depth=4, extra_streams=3,
                              rng_seed=41)
    np.testing.assert_array_equal(d2, base)
    np.testing.assert_array_equal(d4, base)
    np.testing.assert_array_equal(crowd, base)
    np.testing.assert_allclose(d4_lp, base_lp, atol=1e-5)
    np.testing.assert_allclose(crowd_lp, base_lp, atol=1e-5)
    # with a real draft trunk some drafts must actually be accepted —
    # otherwise this test exercises nothing
    assert st["spec"]["accepted"] > 0


def test_spec_stats_block(params):
    """stats()["spec"] reports the acceptance telemetry the dashboard
    aggregates: proposed/accepted counters and the per-pump
    accepted-length histogram."""
    rng = np.random.RandomState(3)
    prompt = rng.randint(1, 250, 7).astype(np.int32)
    _, _, st = _run(params, prompt, 16, temperature=0.8, seed=11,
                    spec_depth=4)
    sp = st["spec"]
    assert sp["depth"] == 4 and sp["draft_layers"] == 1
    assert sp["pumps"] > 0
    assert 0 <= sp["accepted"] <= sp["proposed"]
    assert 0.0 <= sp["acceptance_rate"] <= 1.0
    hist = sp["accept_hist"]
    assert hist and all(isinstance(k, str) for k in hist)
    assert sum(hist.values()) > 0


def test_spec_live_flip_via_config(params):
    """serve_spec_enabled gates speculation and serve_spec_depth
    overrides the constructor depth — consulted at every pump, so an
    operator can flip speculation on a live engine."""
    rng = np.random.RandomState(4)
    prompt = rng.randint(1, 250, 7).astype(np.int32)
    base, _, _ = _run(params, prompt, 12, temperature=0.0, seed=5)
    try:
        _cfg.set_system_config({"serve_spec_enabled": False})
        toks, _, st = _run(params, prompt, 12, temperature=0.0, seed=5,
                           spec_depth=4)
        np.testing.assert_array_equal(toks, base)
        assert st["spec"]["pumps"] == 0  # gated off: plain path ran
        _cfg.set_system_config({"serve_spec_enabled": True,
                                "serve_spec_depth": 2})
        toks, _, st = _run(params, prompt, 12, temperature=0.0, seed=5,
                           spec_depth=0)  # ctor says off; config wins
        np.testing.assert_array_equal(toks, base)
        assert st["spec"]["pumps"] > 0
    finally:
        _cfg.set_system_config({"serve_spec_enabled": True,
                                "serve_spec_depth": 0})


def test_spec_verify_chaos_drop_falls_back_exact(params):
    """A "drop" at serve.spec_verify degrades that pump to the plain
    kernel — retryable by construction, because the plain path emits
    the exact same tokens.  A bounded "delay" only adds latency."""
    rng = np.random.RandomState(6)
    prompt = rng.randint(1, 250, 7).astype(np.int32)
    base, _, _ = _run(params, prompt, 16, temperature=0.9, seed=31)
    try:
        fi.configure([
            {"site": "serve.spec_verify", "action": "drop", "count": 2},
            {"site": "serve.spec_verify", "action": "delay",
             "delay_s": 0.02, "after": 2, "count": 1},
        ])
        toks, _, st = _run(params, prompt, 16, temperature=0.9,
                           seed=31, spec_depth=4)
        drops = [h for h in fi.hits() if h["action"] == "drop"]
        assert len(drops) == 2
        np.testing.assert_array_equal(toks, base)
        # the dropped pumps ran plain; later pumps speculated again
        assert st["spec"]["pumps"] > 0
    finally:
        fi.clear()


def test_draft_head_zero_init_is_identity(params):
    """mlp.init_draft_head zero-inits the out-projection, so the
    residual adapter is an exact identity at init — an engine built
    with the head stays bit-identical to one without it."""
    head = mlp.init_draft_head(TINY.d_model, jax.random.PRNGKey(7))
    h = jax.random.normal(jax.random.PRNGKey(8), (3, TINY.d_model))
    np.testing.assert_array_equal(
        np.asarray(mlp.apply_draft_head(head, h)), np.asarray(h))
    rng = np.random.RandomState(9)
    prompt = rng.randint(1, 250, 7).astype(np.int32)
    base, _, _ = _run(params, prompt, 12, temperature=0.0, seed=5)
    toks, _, _ = _run(params, prompt, 12, temperature=0.0, seed=5,
                      spec_depth=2, draft_head=head)
    np.testing.assert_array_equal(toks, base)
