"""Ring-collective engine unit tests (no cluster).

Runs N ranks as threads over an in-memory mailbox that round-trips every
frame through the real wire serialization, so the numerics, chunk
geometry, codec framing, and wire-byte accounting are exactly what the
RPC path ships — without paying actor spin-up for the full
world-size × dtype matrix.
"""

import threading
import time

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu.collective import collective as col
from ray_tpu.collective import compression, ring


class _Net:
    """Shared mailbox for all fake ranks, keyed (dst, group, seq, src, tag)."""

    def __init__(self):
        self.cond = threading.Condition()
        self.msgs = {}

    def put(self, key, val):
        with self.cond:
            self.msgs[key] = val
            self.cond.notify_all()

    def take(self, key, timeout):
        deadline = time.monotonic() + timeout
        with self.cond:
            while key not in self.msgs:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise TimeoutError(key)
                self.cond.wait(min(rem, 0.2))
            return self.msgs.pop(key)


class _FakeGroup:
    """Duck-typed Group exposing the transport surface ring.py uses."""

    def __init__(self, net, name, world, rank):
        self.net = net
        self.name = name
        self.world_size = world
        self.rank = rank
        self.seq = 0

    def _next_seq(self):
        self.seq += 1
        return self.seq

    def _send_obj(self, dst, seq, tag, obj, fire=False):
        self.net.put((dst, self.name, seq, self.rank, tag),
                     serialization.pack_payload(obj))

    def _recv_obj(self, src, seq, tag, timeout=None, op=None):
        msg = self.net.take((self.rank, self.name, seq, src, tag),
                            timeout or 30)
        return serialization.unpack_payload(msg)


def run_world(world, fn, name="t"):
    """Run fn(group, rank) on `world` threaded ranks; return rank-ordered
    results, re-raising the first failure."""
    net = _Net()
    outs = [None] * world
    errs = []

    def go(r):
        try:
            outs[r] = fn(_FakeGroup(net, name, world, r), r)
        except Exception as e:  # noqa: BLE001 — surfaced via errs
            errs.append(e)

    threads = [threading.Thread(target=go, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    if errs:
        raise errs[0]
    ring.purge_group(name)
    return outs


@pytest.fixture(autouse=True)
def _clean_ef():
    yield
    ring.purge_group("t")


# ------------------------- numerics matrix -------------------------


@pytest.mark.parametrize("world", [1, 2, 3, 8])
@pytest.mark.parametrize("dtype", ["float32", "int32", "bfloat16"])
def test_ring_allreduce_matches_numpy(world, dtype):
    rng = np.random.default_rng(world)
    import ml_dtypes

    dt = np.dtype(ml_dtypes.bfloat16) if dtype == "bfloat16" \
        else np.dtype(dtype)
    # 37 elements: ragged segments AND a ragged last chunk at tiny chunks
    if dt.kind == "i":
        data = [rng.integers(-40, 40, 37).astype(dt) for _ in range(world)]
    else:
        data = [(rng.standard_normal(37) * 5).astype(dt)
                for _ in range(world)]
    ref = np.sum(np.stack([d.astype(np.float64) for d in data]), axis=0)

    outs = run_world(world, lambda g, r: ring.ring_allreduce(
        g, data[r], op="sum", codec="none", chunk_bytes=16))
    for o in outs:
        assert o.dtype == dt and o.shape == (37,)
        rtol = 0.05 if dtype == "bfloat16" else 1e-6
        np.testing.assert_allclose(o.astype(np.float64), ref, rtol=rtol,
                                   atol=0.5 * world if dtype == "bfloat16"
                                   else 1e-6)
    if dt.kind == "i":
        for o in outs:
            assert np.array_equal(o.astype(np.int64),
                                  ref.astype(np.int64))


def test_chunking_is_sum_order_stable():
    """Any chunk size must produce bit-identical f32 results: chunk
    boundaries never change per-element accumulation order."""
    world = 4
    rng = np.random.default_rng(7)
    data = [rng.standard_normal(1001).astype(np.float32)
            for _ in range(world)]
    tiny = run_world(world, lambda g, r: ring.ring_allreduce(
        g, data[r], codec="none", chunk_bytes=8))
    huge = run_world(world, lambda g, r: ring.ring_allreduce(
        g, data[r], codec="none", chunk_bytes=1 << 26))
    for a, b in zip(tiny, huge):
        assert np.array_equal(a, b)


@pytest.mark.parametrize("op,npop", [
    ("max", np.max), ("min", np.min), ("mean", np.mean), ("prod", np.prod),
])
def test_ring_allreduce_ops(op, npop):
    world = 3
    rng = np.random.default_rng(3)
    data = [rng.standard_normal((5, 4)).astype(np.float32)
            for _ in range(world)]
    outs = run_world(world, lambda g, r: ring.ring_allreduce(
        g, data[r], op=op, codec="none", chunk_bytes=32))
    ref = npop(np.stack(data), axis=0)
    for o in outs:
        np.testing.assert_allclose(o, ref, rtol=1e-5)


def test_ring_reducescatter_own_shard_and_wire_bytes():
    """Each rank receives ONLY its reduced axis-0 shard, with star-parity
    array_split boundaries, and puts at most (N-1)/N of the tensor on the
    wire — the fix for the allreduce-then-slice star implementation."""
    world = 4
    rng = np.random.default_rng(11)
    data = [rng.standard_normal((7, 3)).astype(np.float32)
            for _ in range(world)]
    full = np.sum(np.stack(data), axis=0)
    shards = np.array_split(full, world, axis=0)

    def go(g, r):
        out = ring.ring_reducescatter(g, data[r], op="sum", codec="none",
                                      chunk_bytes=16)
        return out, ring.last_op_stats(g.name)

    outs = run_world(world, go)
    tensor_bytes = data[0].nbytes
    for r, (o, st) in enumerate(outs):
        assert o.shape == shards[r].shape
        np.testing.assert_allclose(o, shards[r], rtol=1e-5, atol=1e-5)
        # reduce-scatter alone: (N-1)/N of the tensor per rank (+ nothing)
        assert st.bytes_sent <= tensor_bytes * (world - 1) / world + 64
        assert st.op == "reducescatter" and st.transport == "ring"


def test_ring_allgather():
    world = 5
    rng = np.random.default_rng(5)
    data = [rng.standard_normal((3, 2)).astype(np.float32)
            for _ in range(world)]
    outs = run_world(world, lambda g, r: ring.ring_allgather(
        g, data[r], codec="none", chunk_bytes=8))
    for o in outs:
        assert len(o) == world
        for r in range(world):
            assert np.array_equal(o[r], data[r])


def test_ring_allreduce_wire_bytes_f32_vs_int8():
    """Accounting the perf floors rely on: ring f32 allreduce moves
    exactly 2*(N-1)/N of the tensor per rank; int8 moves <= 30% of that."""
    world = 4
    nbytes = 256 * 1024
    rng = np.random.default_rng(0)
    data = [rng.standard_normal(nbytes // 4).astype(np.float32)
            for _ in range(world)]

    def go(codec):
        def fn(g, r):
            ring.ring_allreduce(g, data[r], codec=codec)
            return ring.last_op_stats(g.name)
        return run_world(world, fn)

    f32 = go("none")
    int8 = go("int8")
    limit = 2 * (world - 1) / world * nbytes
    for st in f32:
        assert st.bytes_sent == limit
    for st, stf in zip(int8, f32):
        assert st.bytes_sent <= 0.30 * stf.bytes_sent


# ------------------------- codecs -------------------------


def test_codec_roundtrip_exact():
    rng = np.random.default_rng(1)
    arr = rng.standard_normal((17, 5)).astype(np.float32)
    c = compression.get_codec("none")
    out = c.decode(c.encode(arr))
    assert np.array_equal(out, arr) and out.dtype == arr.dtype


def test_int8_codec_blockscaled():
    rng = np.random.default_rng(2)
    # mixed magnitudes across blocks: per-block scales must localize error
    arr = np.concatenate([
        rng.standard_normal(512).astype(np.float32) * 1e-3,
        rng.standard_normal(512).astype(np.float32) * 1e3,
    ])
    c = compression.get_codec("int8")
    frame = c.encode(arr)
    out = c.decode(frame)
    # block-scaled RTN error bound: |err| <= scale/2 = max|block| / 254,
    # per block — the small-magnitude block must NOT inherit the large
    # block's scale
    block = frame["block"]
    for lo in range(0, arr.size, block):
        blk = arr[lo:lo + block]
        bound = np.abs(blk).max() / 254 + 1e-12
        assert np.abs(out[lo:lo + block] - blk).max() <= bound
    # wire size: 1 byte/elem + one f32 scale per block
    assert compression.wire_bytes(frame) <= arr.size + 4 * (arr.size // 512
                                                            + 1)


def test_int8_codec_int_passthrough():
    arr = np.arange(100, dtype=np.int64)
    c = compression.get_codec("int8")
    out = c.decode(c.encode(arr))
    assert np.array_equal(out, arr) and out.dtype == arr.dtype


def test_error_feedback_carries_residual():
    rng = np.random.default_rng(3)
    arr = rng.standard_normal(300).astype(np.float32)
    c = compression.get_codec("int8")
    frame, residual = compression.encode_with_ef(c, arr, None)
    assert residual is not None
    np.testing.assert_allclose(c.decode(frame) + residual, arr,
                               rtol=1e-6, atol=1e-6)
    # lossless codec: no residual tracked
    frame, residual = compression.encode_with_ef(
        compression.get_codec("none"), arr, None)
    assert residual is None


def test_int8_ef_sgd_converges_like_f32():
    """SGD on a quadratic with int8+error-feedback gradient sync reaches
    the same loss as f32 within 2% (the EQuARX claim, in miniature)."""
    rng = np.random.default_rng(4)
    c = rng.standard_normal(512).astype(np.float32)
    finals = {}
    for codec in ("none", "int8"):
        x = np.zeros(512, np.float32)
        for _ in range(50):
            grads = [(x - c) * (1.0 + 0.1 * w) for w in range(2)]
            outs = run_world(2, lambda g, r: ring.ring_allreduce(
                g, grads[r], op="mean", codec=codec, ef_tag="grad"),
                name=f"ef-{codec}")
            np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6,
                                       atol=1e-6)
            x = x - 0.3 * outs[0]
        finals[codec] = 0.5 * float(np.sum((x - c) ** 2))
        ring.purge_group(f"ef-{codec}")
    assert finals["int8"] <= finals["none"] * 1.02 + 1e-6, finals


# ------------------------- mailbox hygiene -------------------------


def test_destroy_purges_mailbox_and_p2p_counters():
    """destroy_collective_group must drop the group's pending frames and
    reset p2p seq counters so a re-initialized same-name group can't
    consume stale data."""
    box = col._mailbox()
    box.put(("doomed", 1, 1, 0, "p2p"), ["stale", [b""]])
    box.put(("doomed", 1, 2, 1, "ar-up"), ["stale", [b""]])
    box.put(("survivor", 1, 1, 0, "p2p"), ["keep", [b""]])

    g = col.Group("doomed", 2, 0, worker=None)
    g.p2p_send[1] = 5
    g.p2p_recv[1] = 7
    col._groups["doomed"] = g
    col.destroy_collective_group("doomed")

    assert not any(k[0] == "doomed" for k in box.msgs)
    assert ("survivor", 1, 1, 0, "p2p") in box.msgs
    assert g.p2p_send == {} and g.p2p_recv == {}
    assert "doomed" not in col._groups
    del box.msgs[("survivor", 1, 1, 0, "p2p")]


def test_epoch_keys_isolate_stale_frames():
    """A frame sent under an old group incarnation must never be consumed
    by a re-initialized same-name group: message keys carry the rendezvous
    epoch, so a late-arriving stale frame misses the new keys."""
    box = col._mailbox()
    old = col.Group("epoch-g", 2, 0, worker=None, epoch=1)
    new = col.Group("epoch-g", 2, 0, worker=None, epoch=2)
    box.put(("epoch-g", 1, 1, 1, "t"),
            serialization.pack_payload(np.arange(3)))
    with pytest.raises(TimeoutError):
        new._recv_obj(1, 1, "t", timeout=0.05)
    got = old._recv_obj(1, 1, "t", timeout=0.05)
    assert np.array_equal(got, np.arange(3))


def test_timeout_error_names_group_rank_and_op():
    g = col.Group("tg", 2, 1, worker=None)
    with pytest.raises(TimeoutError) as ei:
        g._recv_obj(0, 3, "ar:rs0:0", timeout=0.05, op="allreduce")
    msg = str(ei.value)
    assert "tg" in msg and "rank 1" in msg and "allreduce" in msg
    assert "rank 0" in msg and "0.05" in msg
