"""RL stack tests: EnvRunner sampling, PPO learning on a corridor env.

Reference analogs: rllib/tests (scaled). The env is a 1-D corridor: agent
starts at 0, action 1 moves right (+1 reward at the goal), action 0 moves
left; optimal policy always moves right. PPO must clearly improve mean
episode return within a few iterations.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.rl import PPO, PPOConfig
from ray_tpu.rl.learner import compute_gae


class Corridor:
    """5-step corridor; obs = [pos/5, 1]; reward 1.0 at the right end."""

    N = 5

    def __init__(self):
        self.pos = 0

    def reset(self):
        self.pos = 0
        return self._obs()

    def _obs(self):
        return np.array([self.pos / self.N, 1.0], np.float32)

    def step(self, action):
        self.pos += 1 if action == 1 else -1
        self.pos = max(0, self.pos)
        done = self.pos >= self.N
        reward = 1.0 if done else -0.05
        return self._obs(), reward, done, {}


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_gae_shapes_and_values():
    rewards = np.array([0.0, 0.0, 1.0], np.float32)
    values = np.array([0.5, 0.5, 0.5], np.float32)
    dones = np.array([False, False, True])
    adv, ret = compute_gae(rewards, values, dones, last_value=0.0,
                           gamma=1.0, lam=1.0)
    # terminal step: advantage = r - v = 0.5; returns = adv + values
    np.testing.assert_allclose(adv[-1], 0.5, atol=1e-6)
    np.testing.assert_allclose(ret, adv + values)


@pytest.mark.slow  # learning-improvement soak; PPO update path stays
# tier-1 via test_rl_learner_group.test_ppo_with_learner_group and the
# connector-pipeline PPO run in test_rl_sac
def test_ppo_improves_on_corridor(cluster):
    cfg = PPOConfig(
        env_creator=Corridor,
        obs_dim=2,
        n_actions=2,
        num_env_runners=2,
        rollout_steps=200,
        lr=5e-3,
        entropy_coeff=0.0,
    )
    algo = cfg.build()
    first = algo.train()
    assert "episode_return_mean" in first
    rets = [first["episode_return_mean"]]
    for _ in range(8):
        rets.append(algo.train()["episode_return_mean"])
    algo.stop()
    # optimal return = 1.0 - 4*0.05 = 0.8; random policy is far below
    assert max(rets[-3:]) > max(rets[0], 0.0) or rets[-1] > 0.6
    assert rets[-1] > rets[0]
