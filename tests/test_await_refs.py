"""Awaitable ObjectRefs (reference `await ref` / ObjectRef.as_future)."""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_await_ref_in_driver_loop(cluster):
    @ray_tpu.remote
    def slow(x):
        time.sleep(0.2)
        return x * 2

    async def main():
        vals = await asyncio.gather(*(slow.remote(i) for i in range(4)))
        return vals

    assert asyncio.run(main()) == [0, 2, 4, 6]


def test_await_ref_inside_async_actor(cluster):
    @ray_tpu.remote
    def produce(x):
        return x

    @ray_tpu.remote
    class Consumer:
        async def consume(self, refs):
            # refs travel NESTED in a list (top-level auto-resolution
            # doesn't touch them) and are awaited on the actor's loop
            return sum([await r for r in refs])

    c = Consumer.remote()
    refs = [produce.remote(20), produce.remote(22)]
    assert ray_tpu.get(c.consume.remote(list(refs)), timeout=60) == 42


def test_await_error_propagates(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("async-boom")

    async def main():
        with pytest.raises(Exception, match="async-boom"):
            await boom.remote()

    asyncio.run(main())
