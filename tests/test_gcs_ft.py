"""Head (GCS) fault tolerance: persistence + reconnect.

Reference analog: python/ray/tests/test_gcs_fault_tolerance.py — the GCS
restarts with Redis-backed tables and raylets reconnect
(NotifyGCSRestart). Here: file-backed snapshot + agent/driver reconnect.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30},
                persist_path=str(tmp_path / "gcs.snapshot"))
    c.connect()
    yield c
    c.shutdown()


def test_head_restart_preserves_kv_and_named_actors(cluster):
    w = cluster._driver
    w.head.call("kv_put", {"ns": "t", "key": b"k", "value": b"v1"})

    @ray_tpu.remote(num_cpus=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.options(name="survivor", lifetime="detached").remote()
    assert ray_tpu.get(c.bump.remote(), timeout=60) == 1
    time.sleep(1.0)  # let the snapshot loop flush

    cluster.restart_head()

    # KV survived the restart (SyncRpcClient reconnects transparently)
    assert w.head.call("kv_get", {"ns": "t", "key": b"k"}) == b"v1"
    # named actor resolvable again; its worker process never died, so
    # state (n=1) is intact
    h = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(h.bump.remote(), timeout=60) == 2


def test_head_restart_agents_reregister_and_schedule(cluster):
    cluster.restart_head()
    # agents reconnect via the heartbeat loop; new work schedules
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        if alive:
            break
        time.sleep(0.2)
    assert alive

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(2, 3), timeout=60) == 5


def test_head_restart_objects_reannounced(cluster):
    ref = ray_tpu.put(np.arange(300_000))  # plasma-sized
    time.sleep(1.2)  # let the snapshot loop flush (like the kv test):
    # the restored directory then covers the object even when the live
    # re-announce trails a loaded reconnect
    cluster.restart_head()
    # wait for the agent to reconnect + re-register before fetching: the
    # re-announce rides the reconnect path
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if any(n["alive"] for n in ray_tpu.nodes()):
                break
        except Exception:
            pass
        time.sleep(0.2)
    # under full-suite load the agent's re-announce can trail the node
    # registration by several heartbeats; wait for the directory entry
    # itself before fetching (that's the property being tested)
    deadline = time.time() + 90
    while time.time() < deadline:
        try:
            if any(o["object_id"] == ref.binary()
                   for o in ray_tpu.list_objects()):
                break
        except Exception:
            pass
        time.sleep(0.3)
    out = None
    for attempt in (0, 1):
        try:
            out = ray_tpu.get(ref, timeout=90)
            break
        except ray_tpu.GetTimeoutError:
            # full-suite load can stretch the reconnect+replay window
            # past one get budget; one settle-and-retry cycle
            if attempt:
                raise
            time.sleep(5)
    assert out[-1] == 299_999
