"""Head (GCS) fault tolerance: persistence + reconnect.

Reference analog: python/ray/tests/test_gcs_fault_tolerance.py — the GCS
restarts with Redis-backed tables and raylets reconnect
(NotifyGCSRestart). Here: file-backed snapshot + agent/driver reconnect.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster(tmp_path):
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30},
                persist_path=str(tmp_path / "gcs.snapshot"))
    c.connect()
    yield c
    c.shutdown()


def test_head_restart_preserves_kv_and_named_actors(cluster):
    w = cluster._driver
    w.head.call("kv_put", {"ns": "t", "key": b"k", "value": b"v1"})

    @ray_tpu.remote(num_cpus=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.options(name="survivor", lifetime="detached").remote()
    assert ray_tpu.get(c.bump.remote(), timeout=60) == 1
    time.sleep(1.0)  # let the snapshot loop flush

    cluster.restart_head()

    # KV survived the restart (SyncRpcClient reconnects transparently)
    assert w.head.call("kv_get", {"ns": "t", "key": b"k"}) == b"v1"
    # named actor resolvable again; its worker process never died, so
    # state (n=1) is intact
    h = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(h.bump.remote(), timeout=60) == 2


def test_head_restart_agents_reregister_and_schedule(cluster):
    cluster.restart_head()
    # agents reconnect via the heartbeat loop; new work schedules
    deadline = time.time() + 30
    while time.time() < deadline:
        alive = [n for n in ray_tpu.nodes() if n["alive"]]
        if alive:
            break
        time.sleep(0.2)
    assert alive

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(2, 3), timeout=60) == 5


def test_head_restart_objects_reannounced(cluster):
    """Primary copies survive a head restart and a plain get works.

    The head shutting down must NOT be treated as client death: the old
    control plane's disconnect handler used to sweep the driver's refs and
    GC every plasma primary mid-restart (the framework's own shutdown
    masquerading as a cluster-wide failure)."""
    ref = ray_tpu.put(np.arange(300_000))  # plasma-sized
    cluster.restart_head()
    # plain get: the agent heartbeat reconnect re-announces primaries and
    # the fetch path retries internally until the directory converges
    out = ray_tpu.get(ref, timeout=60)
    assert out[-1] == 299_999


def test_head_restart_remote_object_recovered(cluster):
    """Variant: the object's primary lives on a NON-head node; after a head
    restart the driver (on the head node) can still pull it — exercises the
    re-announce + directory-routed transfer path, not just the local read."""
    remote_node = cluster.add_node(resources={"CPU": 2, "widget": 1.0})

    @ray_tpu.remote(resources={"widget": 1.0})
    def produce():
        return np.arange(200_000)

    ref = produce.remote()
    # wait (not get): availability only, so no copy lands on the head node
    ready, _ = ray_tpu.wait([ref], timeout=60)
    assert ready
    assert ref.binary() in remote_node.primaries
    cluster.restart_head()
    # the fetch must route through the rebuilt directory to the remote node
    out = ray_tpu.get(ref, timeout=60)
    assert out[-1] == 199_999


def test_acked_writes_survive_head_crash(cluster, tmp_path):
    """Write-through group commit: once kv_put / actor registration is
    ACKED, the state is already on disk — a head CRASH (no graceful
    final flush) cannot lose it. Asserted by reading the snapshot file
    right after the ack, before any shutdown path runs."""
    import msgpack

    w = cluster._driver
    w.head.call("kv_put", {"ns": "wt", "key": b"durable", "value": b"yes"})

    @ray_tpu.remote(num_cpus=1)
    class Keeper:
        def ping(self):
            return 1

    k = Keeper.options(name="keeper", lifetime="detached").remote()
    assert ray_tpu.get(k.ping.remote(), timeout=60) == 1

    # the snapshot on disk ALREADY contains both acked mutations
    with open(cluster.persist_path, "rb") as f:
        snap = msgpack.unpackb(f.read(), strict_map_key=False)
    flat = repr(snap)
    assert "durable" in flat  # kv write present pre-crash
    assert "keeper" in flat  # named actor present pre-crash
