"""SAC (continuous control) + connector pipeline tests.

Reference test strategy: rllib/algorithms/sac/tests/test_sac.py
(compilation + learning on a toy env) and connectors unit tests
(rllib/connectors/tests). Pendulum swing-up is the standard continuous
benchmark; the learning test asserts significant improvement over the
random-policy baseline, not full convergence (CI budget)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.rl import (
    SAC,
    SACConfig,
    ClipAction,
    FrameStack,
    ObsNormalizer,
    PendulumEnv,
    Pipeline,
    PPO,
    PPOConfig,
)


# ---------------- connectors (pure unit tests) ----------------

def test_obs_normalizer_tracks_running_stats():
    norm = ObsNormalizer()
    rng = np.random.RandomState(0)
    data = rng.normal(loc=5.0, scale=3.0, size=(500, 4))
    out = [norm(x) for x in data]
    # after plenty of samples the output distribution is ~standardized
    tail = np.stack(out[-200:])
    assert np.all(np.abs(tail.mean(axis=0)) < 0.5)
    assert np.all(np.abs(tail.std(axis=0) - 1.0) < 0.5)
    # state round-trips
    norm2 = ObsNormalizer()
    norm2.load_state_dict(norm.state_dict())
    x = rng.normal(5.0, 3.0, size=4)
    norm.frozen = norm2.frozen = True
    np.testing.assert_allclose(norm(x), norm2(x))


def test_frame_stack_constant_shape_and_reset():
    fs = FrameStack(3)
    o1 = fs(np.array([1.0, 2.0]))
    assert o1.shape == (6,)
    np.testing.assert_array_equal(o1, [1, 2, 1, 2, 1, 2])
    o2 = fs(np.array([3.0, 4.0]))
    np.testing.assert_array_equal(o2, [1, 2, 1, 2, 3, 4])
    fs.reset()
    o3 = fs(np.array([9.0, 9.0]))
    np.testing.assert_array_equal(o3, [9, 9, 9, 9, 9, 9])


def test_pipeline_composes_and_clips():
    pipe = Pipeline(FrameStack(2), ObsNormalizer(clip=1.0))
    out = pipe(np.array([100.0]))
    assert out.shape == (2,)
    clip = ClipAction(-2.0, 2.0)
    np.testing.assert_array_equal(clip(np.array([5.0, -7.0, 0.5])),
                                  [2.0, -2.0, 0.5])


def test_sac_action_logp_matches_density():
    """sample_action's log-prob must equal the tanh-Gaussian change of
    variables (finite check against an independent computation)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import sac as sac_mod

    params = sac_mod.init_sac_params(jax.random.PRNGKey(0), 3, 2)
    obs = jax.random.normal(jax.random.PRNGKey(1), (5, 3))
    a, logp = sac_mod.sample_action(
        params["actor"], obs, jax.random.PRNGKey(2), 2.0
    )
    assert a.shape == (5, 2) and logp.shape == (5,)
    assert float(jnp.max(jnp.abs(a))) <= 2.0
    assert np.all(np.isfinite(np.asarray(logp)))


# ---------------- end-to-end learning ----------------

@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.mark.slow  # ~50s of SAC updates; tier-1 has an 870s budget
def test_sac_learns_pendulum(cluster):
    """SAC must climb far above the random-policy baseline (~-1200 avg
    return) on Pendulum — the swing-up is effectively solved around
    -200 (reaches ~-180 by ~8k steps with these hyperparameters)."""
    algo = SACConfig(
        env_creator=lambda: PendulumEnv(seed=1),
        obs_dim=3, action_dim=1, action_scale=2.0,
        num_env_runners=1, rollout_steps=256,
        learning_starts=512, random_steps=1000,
        train_batch_size=256, grad_steps_per_iteration=256,
        reward_scale=0.1,
        seed=0,
    ).build()
    try:
        best = -1e9
        for _ in range(48):
            res = algo.train()
            best = max(best, res["episode_return_mean"])
            if best > -300:
                break
        assert best > -500, f"best={best}"
    finally:
        algo.stop()


def test_sac_runs_with_connector_pipeline(cluster):
    """SAC threading the env_to_module connector API end to end (the
    action path always runs ClipAction; here the obs path runs a
    normalizer too). Smoke: iterations complete and losses are finite —
    a MOVING normalization under a replay buffer is a known
    representation-drift trade, so no learning bar here."""
    algo = SACConfig(
        env_creator=lambda: PendulumEnv(seed=2),
        obs_dim=3, action_dim=1, action_scale=2.0,
        num_env_runners=1, rollout_steps=128,
        learning_starts=128, random_steps=128,
        train_batch_size=64, grad_steps_per_iteration=16,
        connectors=lambda: Pipeline(ObsNormalizer()),
        seed=0,
    ).build()
    try:
        res = None
        for _ in range(4):
            res = algo.train()
        assert np.isfinite(res["critic_loss"])
        assert np.isfinite(res["episode_return_mean"])
        # the runner's connector accumulated real statistics
        state = ray_tpu.get(
            algo.runners[0].connector_state.remote(), timeout=30)
        assert state["0"]["count"] > 300
    finally:
        algo.stop()


def test_ppo_runs_with_connector_pipeline(cluster):
    """PPO threading the same connector API: FrameStack(2) doubles the
    obs width and the policy trains against the stacked view."""

    class ChainEnv:
        """Move right (+1 reward at the end) or left; 8 states."""

        def __init__(self):
            self.n = 8
            self.s = 0

        def reset(self):
            self.s = 0
            return self._obs()

        def _obs(self):
            v = np.zeros(4, np.float32)
            v[self.s % 4] = 1.0
            v[3] = self.s / self.n
            return v

        def step(self, a):
            self.s = min(self.n - 1, self.s + 1) if a == 1 else max(
                0, self.s - 1)
            done = self.s == self.n - 1
            return self._obs(), (1.0 if done else -0.01), done, {}

    algo = PPOConfig(
        env_creator=ChainEnv,
        obs_dim=8,  # 4 raw x FrameStack(2)
        n_actions=2,
        num_env_runners=2,
        rollout_steps=64,
        connectors=lambda: Pipeline(FrameStack(2)),
    ).build()
    try:
        last = None
        for _ in range(12):
            last = algo.train()
        assert last["episode_return_mean"] > 0.0, last
    finally:
        algo.stop()


def test_sac_learner_group_parity(cluster):
    """The distributed SAC update equals the single-learner update on
    the full batch: reparameterization noise rides the batch rows, so
    2 replicas' row-weighted allreduced gradient IS the full-batch
    gradient (the SACLearnerGroup contract, rl/learner_group.py)."""
    import jax

    from ray_tpu.rl.learner_group import SACLearnerGroup
    from ray_tpu.rl.sac import SACLearner

    obs_dim, action_dim, n = 3, 1, 64
    rng = np.random.RandomState(0)
    key = jax.random.PRNGKey(42)
    ka, kt = jax.random.split(key)
    batch = {
        "obs": rng.randn(n, obs_dim).astype(np.float32),
        "actions": np.tanh(rng.randn(n, action_dim)).astype(np.float32),
        "rewards": rng.randn(n).astype(np.float32),
        "next_obs": rng.randn(n, obs_dim).astype(np.float32),
        "dones": (rng.rand(n) < 0.1),
        "noise_pi": np.asarray(
            jax.random.normal(ka, (n, action_dim)), np.float32),
        "noise_next": np.asarray(
            jax.random.normal(kt, (n, action_dim)), np.float32),
    }

    single = SACLearner(obs_dim, action_dim, seed=7)
    for _ in range(3):
        single.update(dict(batch))

    group = SACLearnerGroup(obs_dim, action_dim, num_learners=2, seed=7)
    try:
        for _ in range(3):
            group.update(dict(batch))
        got = group.get_weights()
    finally:
        group.shutdown()

    want = single.get_weights()
    flat_w, _ = jax.tree_util.tree_flatten(want)
    flat_g, _ = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(np.asarray, got))
    for a, b in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(a), b, atol=1e-5,
                                   rtol=1e-4)
