"""Deployment graphs (reference serve/tests/test_deployment_graph*.py,
scaled): diamond composition, replica-to-replica ref flow, shared nodes.
"""

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    serve.start()
    yield c
    serve.shutdown()
    c.shutdown()


def test_chain_graph(cluster):
    @serve.deployment
    class Tokenize:
        def __call__(self, text):
            return text.split()

    @serve.deployment
    class Count:
        def __call__(self, tokens):
            return len(tokens)

    inp = serve.InputNode()
    tok = Tokenize.bind()
    cnt = Count.bind()
    graph = cnt.bind(tok.bind(inp))
    h = serve.run_graph(graph)
    assert ray_tpu.get(h.remote("a b c d"), timeout=60) == 4


def test_diamond_graph_with_methods(cluster):
    @serve.deployment(name="preproc")
    class Pre:
        def split(self, s):
            return [int(x) for x in s.split(",")]

    @serve.deployment(name="left")
    class Left:
        def __call__(self, xs):
            return sum(xs)

    @serve.deployment(name="right")
    class Right:
        def __call__(self, xs):
            return max(xs)

    @serve.deployment(name="combine")
    class Combine:
        def __init__(self, scale=1):
            self.scale = scale

        def merge(self, a, b):
            return self.scale * (a + b)

    inp = serve.InputNode()
    pre = Pre.bind()
    xs = pre.split.bind(inp)  # shared node feeding both branches
    out = Combine.bind(10).merge.bind(
        Left.bind().bind(xs), Right.bind().bind(xs)
    )
    h = serve.run_graph(out)
    # sum=6, max=3 -> 10*(6+3) = 90
    assert ray_tpu.get(h.remote("1,2,3"), timeout=60) == 90


def test_duplicate_bind_nodes_stay_distinct(cluster):
    @serve.deployment(name="scale")
    class Scale:
        def __init__(self, k):
            self.k = k

        def __call__(self, x):
            return self.k * x

    @serve.deployment(name="addpair")
    class AddPair:
        def merge(self, a, b):
            return a + b

    inp = serve.InputNode()
    a = Scale.bind(10)   # two bound instances of the SAME deployment
    b = Scale.bind(100)  # must NOT collapse into one
    out = AddPair.bind().merge.bind(a.bind(inp), b.bind(inp))
    h = serve.run_graph(out)
    assert ray_tpu.get(h.remote(3), timeout=60) == 330


def test_unbuilt_graph_raises(cluster):
    @serve.deployment(name="orphan")
    class Orphan:
        def __call__(self, x):
            return x

    node = Orphan.bind().bind(serve.InputNode())
    with pytest.raises(RuntimeError, match="not built"):
        node._execute({}, ("x",))
