"""TorchTrainer: DDP/gloo gang training parity (reference
train/torch/torch_trainer.py tests, scaled)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import ScalingConfig, TorchTrainer


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_torch_ddp_linear_regression(cluster):
    def loop(config):
        import torch
        import torch.distributed as dist

        from ray_tpu.train import prepare_model, session

        torch.manual_seed(session.get_world_rank())
        model = prepare_model(torch.nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        # rank-sharded synthetic data for y = x @ w_true
        g = torch.Generator().manual_seed(42 + session.get_world_rank())
        x = torch.randn(64, 4, generator=g)
        w_true = torch.tensor([[1.0], [-2.0], [3.0], [0.5]])
        y = x @ w_true
        loss = None
        for _ in range(config["steps"]):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()  # DDP all-reduces grads across the gang
            opt.step()
        # verify every rank converged to the SAME weights (DDP sync)
        w = [p.detach().clone() for p in model.parameters()]
        flat = torch.cat([t.flatten() for t in w])
        gathered = [torch.zeros_like(flat) for _ in range(dist.get_world_size())]
        dist.all_gather(gathered, flat)
        max_diff = max(
            float((gathered[0] - g_).abs().max()) for g_ in gathered
        )
        session.report(
            {"loss": float(loss), "weight_divergence": max_diff,
             "world_size": dist.get_world_size()},
            checkpoint={"w": flat.numpy()},
        )

    result = TorchTrainer(
        loop,
        train_loop_config={"steps": 120},
        scaling_config=ScalingConfig(num_workers=2),
    ).fit()
    assert result.metrics["world_size"] == 2
    assert result.metrics["loss"] < 1e-2
    assert result.metrics["weight_divergence"] < 1e-6
    w = result.checkpoint["w"]
    np.testing.assert_allclose(
        w[:4], [1.0, -2.0, 3.0, 0.5], atol=0.15
    )
