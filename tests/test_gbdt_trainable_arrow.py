"""GBDT trainer + class Trainable API + Arrow blocks (VERDICT Missing #7
+ 2.10 Trainable row + 2.11 Arrow block row)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu import tune
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 6, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _toy_frame(n=300, seed=0):
    import pandas as pd

    rng = np.random.RandomState(seed)
    x0 = rng.randn(n)
    x1 = rng.randn(n)
    y = 3.0 * x0 - 2.0 * x1 + rng.randn(n) * 0.1
    return pd.DataFrame({"x0": x0, "x1": x1, "y": y})


def test_gbdt_trainer_with_early_stopping(cluster):
    from ray_tpu.train.gbdt import GBDTPredictor, GBDTTrainer

    df = _toy_frame(400)
    train = rdata.from_items(df.iloc[:300].to_dict("records"))
    valid = rdata.from_items(df.iloc[300:].to_dict("records"))

    result = GBDTTrainer(
        datasets={"train": train, "valid": valid}, label_column="y",
        params={"learning_rate": 0.2, "max_depth": 3},
        num_boost_round=80, rounds_per_report=10,
        early_stopping_rounds=30, mode="regression",
    ).fit()
    assert result.metrics["valid_score"] > 0.9  # R^2 on an easy linear fn
    assert len(result.metrics["history"]) >= 2  # per-round reports exist
    assert result.metrics["best_iteration"] > 0

    # predictor round-trips through the directory checkpoint
    pred = GBDTPredictor.from_checkpoint(result.checkpoint)
    X = df.iloc[300:][["x0", "x1"]].to_numpy()
    out = pred.predict(X)
    assert np.corrcoef(out, df.iloc[300:]["y"])[0, 1] > 0.95


def test_gbdt_classification(cluster):
    from ray_tpu.train.gbdt import GBDTTrainer

    rng = np.random.RandomState(1)
    rows = [{"a": float(a), "b": float(b), "label": int(a + b > 0)}
            for a, b in rng.randn(200, 2)]
    result = GBDTTrainer(
        datasets={"train": rdata.from_items(rows)}, label_column="label",
        num_boost_round=30, mode="classification",
    ).fit()
    assert result.metrics["train_score"] > 0.9


def test_batch_predictor_over_dataset_with_gbdt(cluster):
    """Generic BatchPredictor path with the GBDT predictor over Dataset
    blocks (the 'generic Predictor/BatchPredictor' half of Missing #7)."""
    from ray_tpu.train.gbdt import GBDTPredictor, GBDTTrainer

    df = _toy_frame(200, seed=3)
    train = rdata.from_items(df.to_dict("records"))
    result = GBDTTrainer(datasets={"train": train}, label_column="y",
                         num_boost_round=40).fit()
    ckpt = result.checkpoint

    features = rdata.from_numpy(df[["x0", "x1"]].to_numpy(), parallelism=4)
    pred_ds = features.map_batches(
        lambda b, _c=ckpt: GBDTPredictor.from_checkpoint(_c).predict(b))
    preds = np.concatenate(list(pred_ds.iter_batches()))
    assert preds.shape == (200,)
    assert np.corrcoef(preds, df["y"])[0, 1] > 0.95


class _Quadratic(tune.Trainable):
    checkpoint_frequency = 1

    def setup(self, config):
        self.x = config["x"]
        self.i = 0

    def step(self):
        self.i += 1
        return {"loss": (self.x - 0.5) ** 2 + 1.0 / self.i, "iter": self.i}

    def save_checkpoint(self, d):
        import json
        import os

        with open(os.path.join(d, "state.json"), "w") as f:
            json.dump({"i": self.i}, f)

    def load_checkpoint(self, d):
        import json
        import os

        with open(os.path.join(d, "state.json")) as f:
            self.i = json.load(f)["i"]


def test_class_trainable_with_scheduler(cluster):
    """Class Trainable API: ASHA drives step()/checkpointing like a
    function trainable (reference trainable/trainable.py:106)."""
    tuner = tune.Tuner(
        _Quadratic,
        param_space={"x": tune.grid_search([0.0, 0.5, 1.5])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", max_concurrent_trials=3,
            scheduler=tune.ASHAScheduler(max_t=8, grace_period=2),
        ),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.config["x"] == 0.5
    # every trial produced checkpoints through the class hooks
    assert any(r.checkpoint is not None for r in grid)


def test_arrow_blocks(cluster, tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = pa.table({"a": list(range(100)), "b": [i * 0.5 for i in range(100)]})
    ds = rdata.from_arrow(table, parallelism=4)
    assert ds.count() == 100
    rows = list(ds.iter_rows())
    assert rows[3] == {"a": 3, "b": 1.5}

    # arrow blocks flow through map/filter/sort like any other block type
    out = (ds.filter(lambda r: r["a"] % 2 == 0)
           .map_batches(lambda t: t))
    assert out.count() == 50

    # arrow-native parquet read
    pq.write_table(table, tmp_path / "t.parquet")
    ds2 = rdata.read_parquet(str(tmp_path / "t.parquet"), use_arrow=True)
    blocks = list(ds2.iter_batches())
    assert isinstance(blocks[0], pa.Table)
    assert sum(len(b) for b in blocks) == 100
