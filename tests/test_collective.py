"""Collective module tests.

Covers the DCN/CPU process-group backend (KV rendezvous + RPC tree ops;
reference python/ray/util/collective tests) across >=4 executor processes,
and the compiler-native mesh_ops parity vs jnp on the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import ray_tpu
from ray_tpu.collective import collective as col
from ray_tpu.collective import mesh_ops


WORLD = 4


@ray_tpu.remote(num_cpus=0)
class Rank(col.CollectiveActorMixin):
    """One collective rank; each method runs one collective op. The group
    name comes from the mixin's init hook (unique per test to avoid stale
    KV rendezvous entries from earlier groups)."""

    @property
    def g(self):
        return self._coll_group

    def allreduce(self, value, op):
        return col.allreduce(np.asarray(value), self.g, op=op)

    def allreduce_via(self, value, op, transport, codec=None):
        from ray_tpu.collective import ring

        out = col.allreduce(np.asarray(value), self.g, op=op,
                            transport=transport, codec=codec)
        st = ring.last_op_stats(self.g)
        return out, st.transport, st.bytes_sent

    def reducescatter_via(self, value, transport):
        from ray_tpu.collective import ring

        out = col.reducescatter(np.asarray(value), self.g,
                                transport=transport)
        st = ring.last_op_stats(self.g)
        return out, st.bytes_sent

    def broadcast(self, value, src):
        return col.broadcast(np.asarray(value), src_rank=src,
                             group_name=self.g)

    def reduce(self, value, dst):
        return col.reduce(np.asarray(value), dst_rank=dst, group_name=self.g)

    def allgather(self, value):
        return col.allgather(np.asarray(value), self.g)

    def reducescatter(self, value):
        return col.reducescatter(np.asarray(value), self.g)

    def barrier_then(self, value):
        col.barrier(self.g)
        return value

    def rank_info(self):
        return col.get_rank(self.g), col.get_collective_group_size(self.g)

    def sendto(self, dst, value):
        col.send(np.asarray(value), dst, self.g)
        return True

    def recvfrom(self, src):
        return col.recv(src, self.g)


@pytest.fixture(scope="module")
def cluster():
    from ray_tpu.cluster_utils import Cluster

    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.fixture
def group(cluster):
    import uuid

    actors = [Rank.remote() for _ in range(WORLD)]
    ranks = col.create_collective_group(actors, WORLD, list(range(WORLD)),
                                        group_name=uuid.uuid4().hex[:8])
    assert sorted(ranks) == list(range(WORLD))
    yield actors
    for a in actors:
        ray_tpu.kill(a)


def _call_all(actors, method, *args_per_rank):
    refs = [getattr(a, method).remote(*args) for a, args in
            zip(actors, args_per_rank)]
    return ray_tpu.get(refs, timeout=120)


def test_rank_and_size(group):
    infos = ray_tpu.get([a.rank_info.remote() for a in group], timeout=60)
    assert sorted(r for r, _ in infos) == list(range(WORLD))
    assert all(s == WORLD for _, s in infos)


@pytest.mark.parametrize("op,expect", [
    ("sum", sum(range(WORLD))),
    ("max", WORLD - 1),
    ("min", 0),
])
def test_allreduce(group, op, expect):
    outs = _call_all(group, "allreduce",
                     *[(np.full((3, 2), float(r)), op) for r in range(WORLD)])
    for o in outs:
        np.testing.assert_allclose(o, np.full((3, 2), float(expect)))


def test_broadcast(group):
    outs = _call_all(group, "broadcast",
                     *[(np.full(4, float(r + 10)), 2) for r in range(WORLD)])
    for o in outs:
        np.testing.assert_allclose(o, np.full(4, 12.0))


def test_reduce(group):
    outs = _call_all(group, "reduce",
                     *[(np.full(2, float(r)), 1) for r in range(WORLD)])
    np.testing.assert_allclose(outs[1], np.full(2, float(sum(range(WORLD)))))
    # non-dst ranks return their input unchanged
    np.testing.assert_allclose(outs[0], np.zeros(2))


def test_allgather(group):
    outs = _call_all(group, "allgather",
                     *[(np.full(2, float(r)),) for r in range(WORLD)])
    for o in outs:
        assert len(o) == WORLD
        for r, part in enumerate(o):
            np.testing.assert_allclose(part, np.full(2, float(r)))


def test_reducescatter(group):
    # input has world_size rows; each rank keeps its reduced row shard
    outs = _call_all(
        group, "reducescatter",
        *[(np.arange(WORLD * 2, dtype=np.float64).reshape(WORLD, 2) + r,)
          for r in range(WORLD)],
    )
    base = np.arange(WORLD * 2, dtype=np.float64).reshape(WORLD, 2)
    full = base * WORLD + sum(range(WORLD))
    for r, o in enumerate(outs):
        np.testing.assert_allclose(o, full[r:r + 1])


def test_send_recv(group):
    # independent pairs: 0→1 and 2→3 simultaneously
    r1 = group[1].recvfrom.remote(0)
    r3 = group[3].recvfrom.remote(2)
    s0 = group[0].sendto.remote(1, np.array([7.0]))
    s2 = group[2].sendto.remote(3, np.array([9.0]))
    ray_tpu.get([s0, s2], timeout=60)
    np.testing.assert_allclose(ray_tpu.get(r1, timeout=60), [7.0])
    np.testing.assert_allclose(ray_tpu.get(r3, timeout=60), [9.0])


def test_barrier(group):
    outs = _call_all(group, "barrier_then", *[(r,) for r in range(WORLD)])
    assert sorted(outs) == list(range(WORLD))


def test_star_vs_ring_parity(group):
    """The RAY_TPU_COLLECTIVE_TRANSPORT flag must not change results:
    integer-valued f32 sums are exact on both transports."""
    vals = [np.arange(24.0, dtype=np.float32).reshape(6, 4) * (r + 1)
            for r in range(WORLD)]
    ring_outs = _call_all(group, "allreduce_via",
                          *[(v, "sum", "ring") for v in vals])
    star_outs = _call_all(group, "allreduce_via",
                          *[(v, "sum", "star") for v in vals])
    expect = np.sum(np.stack(vals), axis=0)
    for (ro, rt, _), (so, st_, _) in zip(ring_outs, star_outs):
        assert rt == "ring" and st_ == "star"
        np.testing.assert_array_equal(ro, expect)
        np.testing.assert_array_equal(so, expect)


def test_ring_reducescatter_wire_bytes_on_fabric(group):
    """Over the real RPC fabric, ring reduce-scatter must put at most
    (N-1)/N of the tensor on each rank's wire; the star path re-sends
    the FULL tensor to every rank (root pays N-1 copies)."""
    n = 64 * 1024 // 4
    vals = [np.full(n, float(r), np.float32) for r in range(WORLD)]
    ring_outs = _call_all(group, "reducescatter_via",
                          *[(v, "ring") for v in vals])
    star_outs = _call_all(group, "reducescatter_via",
                          *[(v, "star") for v in vals])
    tensor_bytes = vals[0].nbytes
    expect = np.sum(np.stack(vals), axis=0)
    shards = np.array_split(expect, WORLD, axis=0)
    for r, ((out, sent), (sout, _)) in enumerate(zip(ring_outs, star_outs)):
        np.testing.assert_array_equal(out, shards[r])
        np.testing.assert_array_equal(sout, shards[r])
        assert sent <= tensor_bytes * (WORLD - 1) / WORLD + 256
    # star root pays (N-1) full downlink copies on top of its uplink
    star_root_sent = star_outs[0][1]
    assert star_root_sent >= tensor_bytes * (WORLD - 1)


# ---------------- mesh_ops parity on the 8-device CPU mesh ----------------


@pytest.fixture(scope="module")
def mesh8():
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("x", "y"))


def test_mesh_allreduce_parity(mesh8):
    x = jnp.arange(16.0).reshape(4, 4)
    out = mesh_ops.mesh_allreduce(x, mesh8, "x")
    # replicated input summed over the 4-member x axis
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 4)
    out = mesh_ops.mesh_allreduce(x, mesh8, "x", op="mean")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_mesh_allgather_parity(mesh8):
    full = jnp.arange(8.0).reshape(8, 1)
    sharded = jax.device_put(full, NamedSharding(mesh8, P("x", None)))
    out = mesh_ops.mesh_allgather(sharded, mesh8, "x")
    np.testing.assert_allclose(np.asarray(out), np.asarray(full))


def test_mesh_reducescatter_parity(mesh8):
    x = jnp.arange(8.0).reshape(4, 2)
    out = mesh_ops.mesh_reducescatter(x, mesh8, "x")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 4)


def test_mesh_broadcast_parity(mesh8):
    x = jnp.arange(6.0).reshape(2, 3)
    out = mesh_ops.mesh_broadcast(x, mesh8, "x", root=1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_mesh_ppermute_ring(mesh8):
    # each x-member holds its rank; shift-by-1 ring moves rank r to r+1
    full = jnp.repeat(jnp.arange(4.0), 2).reshape(8, 1)  # member r holds r,r
    x = jax.device_put(full, NamedSharding(mesh8, P("x", None)))
    out = mesh_ops.mesh_ppermute(x, mesh8, "x", shift=1)
    got = np.asarray(out).ravel()
    want = np.repeat((np.arange(4) - 1) % 4, 2)
    np.testing.assert_allclose(got, want)


def test_mesh_all_to_all_parity(mesh8):
    # [heads=4, seq=8]: heads concat on x → seq split on x (Ulysses swap)
    full = jnp.arange(32.0).reshape(4, 8)
    x = jax.device_put(full, NamedSharding(mesh8, P("x", None)))
    out = mesh_ops.mesh_all_to_all(x, mesh8, "x", split_axis=1, concat_axis=0)
    assert out.shape == (4, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full))
