"""IMPALA / V-trace / vectorized sampling / multi-agent env (VERDICT r2
Missing #1: RLlib's structural depth beyond PPO/DQN/BC)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from tests.test_rl import Corridor


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 6, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_vtrace_matches_naive_recursion():
    """lax.scan V-trace vs a straightforward numpy recursion."""
    import jax

    from ray_tpu.rl.vtrace import vtrace

    rng = np.random.RandomState(0)
    T = 12
    mu = -np.abs(rng.randn(T)).astype(np.float32)
    pi = mu + rng.randn(T).astype(np.float32) * 0.3
    r = rng.randn(T).astype(np.float32)
    v = rng.randn(T).astype(np.float32)
    boot = np.float32(0.37)
    dones = np.zeros(T, bool)
    dones[7] = True
    gamma, lam, rho_bar, c_bar = 0.95, 0.9, 1.0, 1.0

    vs, adv = jax.jit(
        lambda *a: vtrace(*a, gamma=gamma, lam=lam, rho_bar=rho_bar,
                          c_bar=c_bar)
    )(mu, pi, r, v, boot, dones)

    # naive reference recursion
    rho = np.minimum(rho_bar, np.exp(pi - mu))
    c = lam * np.minimum(c_bar, np.exp(pi - mu))
    disc = gamma * (1.0 - dones.astype(np.float32))
    nv = np.append(v[1:], boot)
    delta = rho * (r + disc * nv - v)
    # vs_t = V_t + delta_t + disc_t c_t (vs_{t+1} - V_{t+1})
    vs_ref2 = np.zeros(T, np.float32)
    carry = 0.0
    for t in reversed(range(T)):
        carry = delta[t] + disc[t] * c[t] * carry
        vs_ref2[t] = v[t] + carry
    np.testing.assert_allclose(np.asarray(vs), vs_ref2, rtol=1e-5,
                               atol=1e-5)
    nvs = np.append(np.asarray(vs)[1:], boot)
    adv_ref = rho * (r + disc * nvs - v)
    np.testing.assert_allclose(np.asarray(adv), adv_ref, rtol=1e-5,
                               atol=1e-5)


def test_vector_env_runner_batch_shapes(cluster):
    from ray_tpu._private import serialization
    from ray_tpu.rl import models
    from ray_tpu.rl.vector_env import VectorEnvRunner

    import jax

    blob = serialization.pack_callable(Corridor)
    r = VectorEnvRunner.remote(blob, 2, 2, num_envs=3, seed=0)
    w = jax.device_get(models.init_policy(jax.random.PRNGKey(0), 2, 2))
    ray_tpu.get(r.set_weights.remote(w), timeout=120)
    b = ray_tpu.get(r.sample.remote(10), timeout=120)
    assert b["obs"].shape == (10, 3, 2)
    assert b["actions"].shape == (10, 3)
    assert b["last_values"].shape == (3,)
    assert b["dones"].dtype == bool
    ray_tpu.kill(r)


def test_impala_improves_on_corridor(cluster):
    from ray_tpu.rl.impala import IMPALAConfig

    algo = IMPALAConfig(
        env_creator=Corridor, obs_dim=2, n_actions=2,
        num_env_runners=2, num_envs_per_runner=4, rollout_steps=32,
        lr=5e-3, entropy_coeff=0.02,
    ).build()
    try:
        first = algo.train()
        for _ in range(25):
            last = algo.train()
        assert last["training_iteration"] == 26
        # corridor optimum ~0.8 (5 steps * -0.05 + 1.0); random walk is
        # deeply negative. Require clear learning progress.
        assert last["episode_return_mean"] > max(
            first["episode_return_mean"] + 0.3, 0.0), (first, last)
    finally:
        algo.stop()


class _TwoAgentCorridor:
    """Both agents walk corridors; team reward, episode ends when both
    finish (or step budget)."""

    N = 4

    def __init__(self):
        self.pos = {"a": 0, "b": 0}
        self.t = 0

    def reset(self):
        self.pos = {"a": 0, "b": 0}
        self.t = 0
        return {aid: self._obs(aid) for aid in self.pos}

    def _obs(self, aid):
        return np.array([self.pos[aid] / self.N, 1.0], np.float32)

    def step(self, actions: dict):
        self.t += 1
        rewards, dones, obs = {}, {}, {}
        for aid, a in actions.items():
            self.pos[aid] = max(0, self.pos[aid] + (1 if a == 1 else -1))
            done = self.pos[aid] >= self.N
            rewards[aid] = 1.0 if done else -0.02
            dones[aid] = done
            if not done:
                obs[aid] = self._obs(aid)
        dones["__all__"] = (all(dones.get(a, False)
                                for a in ("a", "b")) or self.t >= 40)
        return obs, rewards, dones, {}


def test_multi_agent_shared_policy_ppo(cluster):
    from ray_tpu.rl.multi_agent import SharedPolicyWrapper
    from ray_tpu.rl.ppo import PPOConfig

    algo = PPOConfig(
        env_creator=lambda: SharedPolicyWrapper(_TwoAgentCorridor()),
        obs_dim=2, n_actions=2, num_env_runners=2, rollout_steps=128,
        lr=5e-3,
    ).build()
    try:
        first = algo.train()
        for _ in range(12):
            last = algo.train()
        # shared policy learns to walk right for both agents
        assert last["episode_return_mean"] > first["episode_return_mean"], (
            first["episode_return_mean"], last["episode_return_mean"])
        assert np.isfinite(last["total_loss"])
    finally:
        algo.stop()
