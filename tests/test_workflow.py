"""Durable workflow tests (reference ray.workflow semantics, scaled)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.cluster_utils import Cluster
from ray_tpu.dag import InputNode


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_workflow_runs_and_persists(cluster, tmp_path):
    marker = tmp_path / "exec_count"
    marker.write_text("0")

    @ray_tpu.remote
    def bump_and_add(a, b):
        # count real executions via a shared file
        n = int(open(str(marker)).read())
        open(str(marker), "w").write(str(n + 1))
        return a + b

    @ray_tpu.remote
    def square(x):
        return x * x

    x = InputNode(0)
    s = bump_and_add.bind(x, 3)
    dag = square.bind(s)
    out = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path),
                       args=(4,))
    assert out == 49
    assert int(marker.read_text()) == 1
    # re-running the same workflow id replays from storage: no new execs
    out2 = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path),
                        args=(4,))
    assert out2 == 49
    assert int(marker.read_text()) == 1


def test_workflow_resume_completes_missing_steps(cluster, tmp_path):
    @ray_tpu.remote
    def step_a():
        return 10

    @ray_tpu.remote
    def step_b(a):
        return a + 5

    dag = step_b.bind(step_a.bind())
    # simulate a crash after step_a: run a truncated dag first
    workflow.run(step_a.bind(), workflow_id="wf2",
                 storage=str(tmp_path))
    # full dag under the same id: step_a's result is NOT shared (different
    # structural path), but resume of the full dag picks up its own steps
    workflow.run(dag, workflow_id="wf3", storage=str(tmp_path))
    assert workflow.resume("wf3", storage=str(tmp_path)) == 15


def test_step_options_retry_and_catch(cluster, tmp_path):
    attempts = tmp_path / "attempts"
    attempts.write_text("0")

    @ray_tpu.remote(max_retries=0)
    def flaky(path, fail_times):
        n = int(open(path).read())
        open(path, "w").write(str(n + 1))
        if n < fail_times:
            raise RuntimeError(f"attempt {n} fails")
        return "recovered"

    # workflow-level retries resubmit past runtime failures
    node = workflow.options(flaky.bind(str(attempts), 2), max_retries=3)
    out = workflow.run(node, workflow_id="wopt1", storage=str(tmp_path))
    assert out == "recovered"
    assert int(attempts.read_text()) == 3

    # catch_exceptions: failure becomes a durable (None, exc) value
    attempts2 = tmp_path / "attempts2"
    attempts2.write_text("0")
    node2 = workflow.options(flaky.bind(str(attempts2), 99),
                             catch_exceptions=True)
    val, err = workflow.run(node2, workflow_id="wopt2",
                            storage=str(tmp_path))
    assert val is None and isinstance(err, Exception)
    # and the caught outcome is durable: re-run replays, no new attempts
    n_before = int(attempts2.read_text())
    val2, err2 = workflow.run(node2, workflow_id="wopt2",
                              storage=str(tmp_path))
    assert val2 is None and isinstance(err2, Exception)
    assert int(attempts2.read_text()) == n_before


def test_continuation_tail_call(cluster, tmp_path):
    execs = tmp_path / "execs"
    execs.write_text("")

    @ray_tpu.remote
    def mark(tag, v):
        with open(str(execs), "a") as f:
            f.write(tag + ",")
        return v

    @ray_tpu.remote
    def fib_like(path, n, acc):
        from ray_tpu import workflow as wf
        with open(path, "a") as f:
            f.write(f"fib{n},")
        if n == 0:
            return acc
        return wf.continuation(fib_like.bind(path, n - 1, acc + n))

    dag = mark.bind("post", fib_like.bind(str(execs), 3, 0))
    out = workflow.run(dag, workflow_id="wcont", storage=str(tmp_path))
    assert out == 6  # 3+2+1
    first = execs.read_text()
    assert "fib3," in first and "fib0," in first
    # durable: replay executes nothing new
    out2 = workflow.run(dag, workflow_id="wcont", storage=str(tmp_path))
    assert out2 == 6
    assert execs.read_text() == first


def test_workflow_wait_partial_and_later_completion(cluster, tmp_path):
    @ray_tpu.remote
    def fast():
        return "fast"

    @ray_tpu.remote
    def slow():
        import time as _t

        _t.sleep(5)
        return "slow"

    w = workflow.wait([fast.bind(), slow.bind()], num_returns=1,
                      timeout_s=30)
    ready, pending = workflow.run(w, workflow_id="wwait",
                                  storage=str(tmp_path))
    assert ready == ["fast"]
    assert len(pending) == 1
    # the pending branch runs to completion in a follow-up workflow
    done = workflow.run(pending[0], workflow_id="wwait2",
                        storage=str(tmp_path))
    assert done == "slow"


def test_event_step_durable(cluster, tmp_path):
    import threading

    @ray_tpu.remote
    def combine(ev, suffix):
        return f"{ev}-{suffix}"

    dag = combine.bind(workflow.wait_for_event("go"), "done")

    def poster():
        import time as _t

        _t.sleep(1.0)
        workflow.post_event(str(tmp_path), "wev", "go", "fired")

    t = threading.Thread(target=poster)
    t.start()
    out = workflow.run(dag, workflow_id="wev", storage=str(tmp_path),
                       step_timeout_s=60)
    t.join()
    assert out == "fired-done"
    # resume does NOT re-wait: no new post needed
    assert workflow.resume("wev", storage=str(tmp_path)) == "fired-done"
    assert any(w["workflow_id"] == "wev" and w["status"] == "SUCCESSFUL"
               for w in workflow.list_workflows(str(tmp_path)))
