"""Durable workflow tests (reference ray.workflow semantics, scaled)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.cluster_utils import Cluster
from ray_tpu.dag import InputNode


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 2 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_workflow_runs_and_persists(cluster, tmp_path):
    marker = tmp_path / "exec_count"
    marker.write_text("0")

    @ray_tpu.remote
    def bump_and_add(a, b):
        # count real executions via a shared file
        n = int(open(str(marker)).read())
        open(str(marker), "w").write(str(n + 1))
        return a + b

    @ray_tpu.remote
    def square(x):
        return x * x

    x = InputNode(0)
    s = bump_and_add.bind(x, 3)
    dag = square.bind(s)
    out = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path),
                       args=(4,))
    assert out == 49
    assert int(marker.read_text()) == 1
    # re-running the same workflow id replays from storage: no new execs
    out2 = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path),
                        args=(4,))
    assert out2 == 49
    assert int(marker.read_text()) == 1


def test_workflow_resume_completes_missing_steps(cluster, tmp_path):
    @ray_tpu.remote
    def step_a():
        return 10

    @ray_tpu.remote
    def step_b(a):
        return a + 5

    dag = step_b.bind(step_a.bind())
    # simulate a crash after step_a: run a truncated dag first
    workflow.run(step_a.bind(), workflow_id="wf2",
                 storage=str(tmp_path))
    # full dag under the same id: step_a's result is NOT shared (different
    # structural path), but resume of the full dag picks up its own steps
    workflow.run(dag, workflow_id="wf3", storage=str(tmp_path))
    assert workflow.resume("wf3", storage=str(tmp_path)) == 15
