"""Randomized-seed cluster-wide chaos soak.

Every seed expands (via `ray_tpu._private.chaos.gen_fault_plan`) into a
site-weighted set of deterministic fault specs across the instrumented
sites — ring chunk sends/recvs, collective frames, checkpoint
save/restore, agent heartbeats, object-chunk serving, lease pushes — and
every seed must CONVERGE: training reaches the target step with
loss/parameter parity against the fault-free schedule, no actors or
placement groups leak, and wall-clock stays bounded.

Tier-1 runs `test_soak_smoke` (3 fixed seeds under a hard deadline); the
full randomized sweep (>= 20 seeds) is marked `slow`. Any failing seed
logs the exact `RAY_TPU_FAULT_SPEC` that replays it deterministically.
"""

import sys
import time

import cloudpickle
import numpy as np
import pytest

from ray_tpu._private import fault_injection as fi
from ray_tpu._private.chaos import gen_fault_plan
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

# worker subprocesses can't import the tests package: ship helpers by value
cloudpickle.register_pickle_by_value(sys.modules[__name__])

N_BLOCKS = 8
DIM = 16
LR = 0.1
STEPS = 5
WORLD = 2

# fixed tier-1 seeds, chosen for coverage (see gen_fault_plan expansion):
#   1  -> collective.send delay (noise; fault-free parity)
#   2  -> ring.send exit (hard rank death -> in-place resume)
#   38 -> ring.recv exit + checkpoint.save drop (kill + torn checkpoint
#         -> checksum fallback to the previous checkpoint)
SMOKE_SEEDS = (1, 2, 38)
SMOKE_DEADLINE_S = 120.0  # per seed, generous for a loaded CI box
SOAK_SEEDS = tuple(range(40, 60))
SOAK_DEADLINE_S = 240.0


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()


def _block_grad(i, step):
    rng = np.random.default_rng(7919 * (i + 1) + step)
    return rng.standard_normal(DIM).astype(np.float32)


def _ref_params(steps):
    p = np.zeros(DIM, np.float32)
    for s in range(steps):
        total = np.zeros(DIM, np.float32)
        for i in range(N_BLOCKS):
            total = total + _block_grad(i, s)
        p = p - LR * (total / N_BLOCKS)
    return p


def _soak_loop(config):
    """World-size-invariant training: each step sums the block gradients
    of this rank's shard and ring-sums the totals, so ANY elastic
    world-size trajectory produces the same parameters. Worker-side
    chaos specs arm on the first incarnation only — resumed and respawned
    processes never re-arm, so every plan is finite and must converge."""
    import os as _os

    import numpy as _np

    from ray_tpu._private import fault_injection as _fi
    from ray_tpu.train import dcn_allreduce_grads, session
    from ray_tpu.train.checkpoint import Checkpoint as _Ck

    rank = session.get_world_rank()
    seq = session.get_resume_seq()
    if seq == 0 and config.get("worker_specs"):
        _fi.configure(config["worker_specs"])
    shard = session.get_dataset_shard("train")
    group = session.get_collective_group()
    params = _np.zeros(DIM, _np.float32)
    start = 0
    ck = session.get_checkpoint()
    if ck is not None:
        d = ck.to_dict()
        params = _np.asarray(d["params"], _np.float32)
        start = int(d["step"])
    for step in range(start, config["steps"]):
        contrib = _np.zeros(DIM, _np.float32)
        for i in shard.assigned_indices():
            contrib = contrib + _block_grad(i, step)
        total = dcn_allreduce_grads({"g": contrib}, group, op="sum",
                                    timeout=10.0)["g"]
        params = params - LR * (total / N_BLOCKS)
        ckpt = None
        if rank == 0:
            ckpt = _Ck.from_dict(
                {"step": step + 1, "params": params},
                _os.path.join(config["ck_dir"], f"ck_s{seq}_{step}"))
        session.report({"step": step + 1,
                        "loss": float(_np.square(params).sum())},
                       checkpoint=ckpt)


def _assert_no_leaks(cluster, deadline_s: float = 15.0):
    """No leaked gang state after a soak episode: every actor reached
    DEAD and every placement group was removed (freeing its bundles and
    any objects they pinned)."""
    from ray_tpu.core.control_plane import DEAD

    deadline = time.monotonic() + deadline_s
    while True:
        live = [a for a in cluster.cp.actors.values()
                if a.get("state") != DEAD]
        pgs = dict(cluster.cp.pgs)
        if not live and not pgs:
            return
        if time.monotonic() > deadline:
            raise AssertionError(
                f"leaked cluster state after soak: "
                f"{len(live)} non-DEAD actor(s) "
                f"{[a.get('state') for a in live]}, "
                f"{len(pgs)} placement group(s)")
        time.sleep(0.5)


def _run_seed(cluster, tmp_path, seed: int, deadline_s: float):
    plan = gen_fault_plan(seed, world_size=WORLD, max_faults=2)
    fi.clear()
    if plan.driver_specs:
        fi.configure(plan.driver_specs)
    out = tmp_path / f"seed{seed}"
    out.mkdir()
    trainer = JaxTrainer(
        _soak_loop,
        train_loop_config={
            "steps": STEPS,
            "ck_dir": str(out / "ckpts"),
            "worker_specs": plan.worker_specs,
        },
        scaling_config=ScalingConfig(
            num_workers=WORLD, resources_per_worker={"CPU": 1},
            backend="dcn", min_workers=1, placement_strategy="PACK",
        ),
        run_config=RunConfig(
            name=f"soak{seed}", storage_path=str(out),
            max_failures=4, max_inplace_resumes=12,
        ),
        datasets={"train": list(range(N_BLOCKS))},
    )
    t0 = time.monotonic()
    try:
        result = trainer.fit()
        elapsed = time.monotonic() - t0
        # convergence: target step reached with loss/parameter parity
        # against the fault-free schedule (f32 ring-order tolerance)
        assert result.error is None, result.error
        assert result.metrics["step"] == STEPS, result.metrics
        final = result.checkpoint.to_dict()
        assert final["step"] == STEPS
        ref = _ref_params(STEPS)
        np.testing.assert_allclose(np.asarray(final["params"]), ref,
                                   rtol=1e-5, atol=1e-6)
        assert result.metrics["loss"] == pytest.approx(
            float(np.square(ref).sum()), rel=1e-4)
        # bounded wall-clock
        assert elapsed < deadline_s, (
            f"seed {seed} converged but took {elapsed:.1f}s "
            f"(deadline {deadline_s}s): {plan.describe()}")
        # nothing leaked
        _assert_no_leaks(cluster)
        return result, elapsed
    except BaseException:
        # replay instructions for the exact failure, plus the flight-
        # recorder postmortems (victim + survivor span rings)
        from ray_tpu._private import flight_recorder

        bundles = flight_recorder.latest_bundles()
        print(f"\nCHAOS SOAK FAILURE {plan.describe()}\n"
              f"replay: RAY_TPU_FAULT_SPEC='{plan.env_value()}'\n"
              f"flight-recorder bundles ({flight_recorder.bundle_dir()}):\n"
              + "".join(f"  {b}\n" for b in bundles),
              file=sys.stderr, flush=True)
        raise
    finally:
        fi.clear()


def test_soak_smoke(cluster, tmp_path):
    """Tier-1: 3 fixed seeds (kill / torn-checkpoint / noise) under a
    hard per-seed deadline."""
    for seed in SMOKE_SEEDS:
        result, elapsed = _run_seed(cluster, tmp_path, seed,
                                    SMOKE_DEADLINE_S)
        print(f"smoke seed {seed}: {elapsed:.1f}s "
              f"resumes={result.resumes}")


@pytest.mark.slow
def test_soak_randomized(cluster, tmp_path):
    """The full sweep: >= 20 randomized seeds, every one must converge."""
    report = []
    for seed in SOAK_SEEDS:
        result, elapsed = _run_seed(cluster, tmp_path, seed,
                                    SOAK_DEADLINE_S)
        report.append((seed, elapsed, result.resumes))
    print("\nsoak report (seed, seconds, resumes):")
    for row in report:
        print(f"  {row}")
    assert len(report) == len(SOAK_SEEDS)


def test_fault_plan_is_deterministic():
    """The replay contract: the same seed always expands to the same
    plan (and its env form round-trips through the injection parser)."""
    import json

    for seed in (*SMOKE_SEEDS, 47):
        a = gen_fault_plan(seed, world_size=WORLD, max_faults=2)
        b = gen_fault_plan(seed, world_size=WORLD, max_faults=2)
        assert a.specs == b.specs
        assert a.env_value() == b.env_value()
        fi.configure(json.loads(a.env_value()))  # validates every spec
        fi.clear()


def test_spec_verify_site_only_via_explicit_override():
    """ISSUE-19 satellite: serve.spec_verify is registered for targeted
    speculation soaks but carries NO profile weight — existing
    train/rl/qos/pipeline plans never draw it, so every fixed seed
    recorded before the site existed expands byte-for-byte the same.
    An explicit sites= override drafts it, pinned to one decode
    replica, with actions from its own (all-recoverable) table."""
    from ray_tpu._private.chaos import RL_SITE_ACTIONS, SERVE_SITES

    assert "serve.spec_verify" in SERVE_SITES
    for seed in range(60):
        for profile in ("train", "rl", "qos", "pipeline"):
            plan = gen_fault_plan(seed, world_size=WORLD,
                                  profile=profile)
            assert all(s["site"] != "serve.spec_verify"
                       for s in plan.specs), (seed, profile)
    allowed = {a for a, _ in RL_SITE_ACTIONS["serve.spec_verify"]}
    assert allowed == {"drop", "stall", "delay"}  # never "die"
    only = {"serve.spec_verify": 1.0}
    a = gen_fault_plan(3, world_size=WORLD, profile="rl", sites=only)
    assert a.specs
    for s in a.specs:
        assert s["site"] == "serve.spec_verify"
        assert s["match"]["engine"].startswith("decode-")
        assert s["action"] in allowed
    # replay contract holds for the new site too
    b = gen_fault_plan(3, world_size=WORLD, profile="rl", sites=only)
    assert a.env_value() == b.env_value()


def test_fault_plan_covers_site_space():
    """Across a modest seed range the generator must exercise every
    instrumented site and both fault localities."""
    sites = set()
    for seed in range(200):
        plan = gen_fault_plan(seed, world_size=WORLD, max_faults=2)
        for s in plan.specs:
            sites.add(s["site"])
    from ray_tpu._private.chaos import SITE_WEIGHTS

    assert sites == set(SITE_WEIGHTS)
