"""Nested-task blocking: workers parked in get() release their pool
slot AND their granted CPUs (reference NotifyDirectCallTaskBlocked +
CPU borrow), so parents blocked on children can't wedge the node on
either axis."""

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


def test_cpu_holding_parents_dont_deadlock_children():
    """2-CPU node, two num_cpus=1 parents each blocked on a num_cpus=1
    child: without blocked-task resource release the children never fit
    and the cluster hangs forever."""
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    try:
        @ray_tpu.remote(num_cpus=1)
        def child(x):
            return x * 10

        @ray_tpu.remote(num_cpus=1)
        def parent(x):
            return ray_tpu.get(child.remote(x), timeout=90)

        out = ray_tpu.get([parent.remote(1), parent.remote(2)],
                          timeout=120)
        assert out == [10, 20]
    finally:
        c.shutdown()


def test_recursion_depth_bounded_by_process_ceiling():
    """Deep blocking recursion must not fork-storm the host: the
    backfill spawning stops at the hard ceiling (4x pool cap)."""
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    try:
        @ray_tpu.remote(num_cpus=0)
        def rec(n):
            if n == 0:
                return 0
            return 1 + ray_tpu.get(rec.remote(n - 1), timeout=120)

        depth = 6  # well under the ceiling: must complete
        assert ray_tpu.get(rec.remote(depth), timeout=120) == depth
        agent = c.head_agent
        n_pool = sum(1 for w in agent.workers.values()
                     if w.actor_id is None)
        assert n_pool <= 4 * agent._pool_worker_cap()
    finally:
        c.shutdown()


def test_child_reclaimed_from_blocked_parents_queue():
    """Pipelined dispatch may stack a child onto its own parent's exec
    queue in the window between the parent's submit and its
    worker_blocked fire landing (the guard `not w.blocked` races the
    notification). The parent then parks in get() on a child that sits
    behind it on the same single exec thread — a permanent hang unless
    the agent reclaims the blocked worker's unstarted queue. Pool cap 1
    + a pre-get sleep makes the race deterministic: the child can ONLY
    pipeline onto the parent's worker."""
    from ray_tpu._private import config as _cfg

    old = {k: _cfg.get(k) for k in ("max_pool_workers_per_node",
                                    "worker_lease_enabled")}
    _cfg.set_system_config({"max_pool_workers_per_node": 1,
                            "worker_lease_enabled": False})
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30})
    c.connect()
    try:
        @ray_tpu.remote(num_cpus=0)
        def child():
            return 42

        @ray_tpu.remote(num_cpus=0)
        def parent():
            import time
            ref = child.remote()
            # let the child's dispatch land in THIS worker's exec queue
            # while we are busy-but-not-yet-blocked
            time.sleep(0.8)
            return ray_tpu.get(ref, timeout=60)

        assert ray_tpu.get(parent.remote(), timeout=90) == 42
    finally:
        try:
            c.shutdown()
        finally:
            _cfg.set_system_config(old)
