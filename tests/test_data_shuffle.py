"""Data shuffle ops + datasources (sort / groupby / random_shuffle / IO).

Reference test models: python/ray/data/tests/test_sort.py,
test_groupby.py, test_csv/parquet readers — semantics pinned against
in-memory oracles on the multinode fixture.
"""

import time

import numpy as np
import pandas as pd
import pytest

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_sort_ints(cluster):
    rng = np.random.default_rng(0)
    vals = rng.integers(0, 10_000, 500).tolist()
    ds = rdata.from_items(vals, parallelism=6).sort()
    assert list(ds.iter_rows()) == sorted(vals)


def test_sort_by_key_descending(cluster):
    rows = [{"id": i, "score": (i * 37) % 101} for i in range(200)]
    ds = rdata.from_items(rows, parallelism=5).sort(
        key="score", descending=True
    )
    got = [r["score"] for r in ds.iter_rows()]
    assert got == sorted((r["score"] for r in rows), reverse=True)


def test_sort_callable_key(cluster):
    vals = list(range(100))
    ds = rdata.from_items(vals, parallelism=4).sort(key=lambda x: -x)
    assert list(ds.iter_rows()) == list(reversed(vals))


def test_random_shuffle_permutes(cluster):
    vals = list(range(300))
    ds = rdata.from_items(vals, parallelism=6).random_shuffle(seed=42)
    got = list(ds.iter_rows())
    assert got != vals  # astronomically unlikely to be identity
    assert sorted(got) == vals


def test_groupby_count_and_sum(cluster):
    rows = [{"k": i % 7, "v": i} for i in range(210)]
    ds = rdata.from_items(rows, parallelism=6)
    counts = dict(ds.groupby("k").count().iter_rows())
    assert counts == {k: 30 for k in range(7)}
    sums = dict(ds.groupby("k").sum("v").iter_rows())
    for k in range(7):
        assert sums[k] == sum(i for i in range(210) if i % 7 == k)


def test_groupby_single_block(cluster):
    # num_parts == 1 exchange is the identity path — no partition tasks
    rows = [{"k": i % 3, "v": i} for i in range(12)]
    ds = rdata.from_items(rows, parallelism=1)
    counts = dict(ds.groupby("k").count().iter_rows())
    assert counts == {0: 4, 1: 4, 2: 4}
    assert list(ds.sort(key="v", num_blocks=1).iter_rows()) == rows


def test_groupby_string_keys_cross_worker(cluster):
    # per-process hash() salting would split these groups across
    # partitions; stable_hash must keep each key in exactly one group
    names = ["apple", "pear", "plum", "kiwi"]
    rows = [{"name": names[i % 4]} for i in range(80)]
    ds = rdata.from_items(rows, parallelism=8)
    counts = dict(ds.groupby("name").count().iter_rows())
    assert counts == {n: 20 for n in names}


def test_sort_dataframe_blocks(cluster, tmp_path):
    import pandas as pd

    df = pd.DataFrame({"x": [5, 3, 9, 1], "y": list("abcd")})
    df.to_csv(tmp_path / "f.csv", index=False)
    ds = rdata.read_csv(str(tmp_path / "f.csv"))
    assert [r["x"] for r in ds.sort(key="x").iter_rows()] == [1, 3, 5, 9]
    assert ds.limit(2).count() == 2


def test_groupby_map_groups(cluster):
    rows = [{"k": i % 3, "v": float(i)} for i in range(30)]
    ds = rdata.from_items(rows, parallelism=3)
    means = sorted(
        ds.groupby(lambda r: r["k"]).map_groups(
            lambda rs: round(sum(r["v"] for r in rs) / len(rs), 3)
        ).iter_rows()
    )
    assert len(means) == 3


def test_aggregates(cluster):
    vals = list(range(1, 101))
    ds = rdata.from_items(vals, parallelism=4)
    assert ds.sum() == 5050
    assert ds.min() == 1
    assert ds.max() == 100
    assert ds.mean() == 50.5


def _fused_task_count():
    return sum(
        1 for t in ray_tpu.list_tasks()
        if t.get("name") == "_map_block_fused"
    )


def test_map_chain_fuses_into_one_task_per_block(cluster):
    ds = rdata.from_items(list(range(40)), parallelism=4)
    before = _fused_task_count()
    out = (
        ds.map_batches(lambda b: [x + 1 for x in b])
          .map_batches(lambda b: [x * 2 for x in b])
          .map_batches(lambda b: [x - 1 for x in b])
    )
    # lazy: nothing ran yet
    assert _fused_task_count() == before
    assert sorted(out.iter_rows()) == sorted((x + 1) * 2 - 1
                                             for x in range(40))
    deadline = time.time() + 10
    while time.time() < deadline:  # task events are async
        ran = _fused_task_count() - before
        if ran >= 4:
            break
        time.sleep(0.2)
    # 3 chained stages x 4 blocks fused to 4 tasks, not 12
    assert ran == 4, f"expected 4 fused tasks, saw {ran}"


def test_branch_shares_materialized_ancestor(cluster):
    # d2 branches off d1 BEFORE d1 materializes; once d1 runs, d2 must
    # reuse d1's cached blocks (nondeterministic stages can't re-run)
    import uuid

    def tag(block):
        return [(row, uuid.uuid4().hex) for row in block]

    ds = rdata.from_items(list(range(8)), parallelism=2)
    d1 = ds.map_batches(tag)
    d2 = d1.map_batches(lambda b: [t for (_, t) in b])
    tags_d1 = {t for (_, t) in d1.iter_rows()}  # materializes d1
    tags_d2 = set(d2.iter_rows())
    assert tags_d2 == tags_d1  # same uuids -> tag() ran exactly once


def test_lazy_dataset_reuse_executes_once(cluster):
    ds = rdata.from_items(list(range(12)), parallelism=2)
    mapped = ds.map_batches(lambda b: [x * 10 for x in b])
    assert mapped.count() == 12
    time.sleep(0.5)  # drain async task events
    before = _fused_task_count()
    assert sorted(mapped.iter_rows())[-1] == 110  # cached, no new tasks
    time.sleep(0.5)
    assert _fused_task_count() == before


def test_union_limit(cluster):
    a = rdata.from_items([1, 2, 3], parallelism=1)
    b = rdata.from_items([4, 5, 6], parallelism=1)
    assert list(a.union(b).iter_rows()) == [1, 2, 3, 4, 5, 6]
    assert list(a.union(b).limit(4).iter_rows()) == [1, 2, 3, 4]


def test_csv_roundtrip(cluster, tmp_path):
    df = pd.DataFrame({"x": range(50), "y": [i * 2.5 for i in range(50)]})
    src = tmp_path / "in.csv"
    df.to_csv(src, index=False)
    ds = rdata.read_csv(str(src))
    out = ds.to_pandas()
    pd.testing.assert_frame_equal(out, df)
    paths = ds.write_csv(str(tmp_path / "out"))
    assert len(paths) == 1
    pd.testing.assert_frame_equal(pd.read_csv(paths[0]), df)


def test_parquet_roundtrip_multifile(cluster, tmp_path):
    df = pd.DataFrame({"a": range(40), "b": list("wxyz") * 10})
    halves = [df.iloc[:20], df.iloc[20:].reset_index(drop=True)]
    for i, h in enumerate(halves):
        h.to_parquet(tmp_path / f"part{i}.parquet")
    ds = rdata.read_parquet(str(tmp_path / "part*.parquet"))
    assert ds.num_blocks() == 2
    got = ds.to_pandas()
    pd.testing.assert_frame_equal(got, df)


def test_jsonl_and_text(cluster, tmp_path):
    rows = [{"n": i, "s": f"row{i}"} for i in range(10)]
    src = tmp_path / "in.jsonl"
    pd.DataFrame(rows).to_json(src, orient="records", lines=True)
    ds = rdata.read_json(str(src))
    assert ds.to_pandas()["n"].tolist() == list(range(10))
    txt = tmp_path / "t.txt"
    txt.write_text("alpha\nbeta\ngamma\n")
    assert list(rdata.read_text(str(txt)).iter_rows()) == [
        "alpha", "beta", "gamma"
    ]


def test_from_pandas_and_torch(cluster):
    df = pd.DataFrame({"v": np.arange(16, dtype=np.float32)})
    ds = rdata.from_pandas(df, parallelism=4)
    assert ds.num_blocks() == 4
    ds2 = rdata.from_numpy(np.arange(12, dtype=np.float32))
    batches = list(ds2.iter_torch_batches())
    total = sum(float(b.sum()) for b in batches)
    assert total == float(np.arange(12).sum())


def test_push_based_shuffle_many_blocks(cluster):
    """Above PUSH_SHUFFLE_THRESHOLD map blocks the exchange merges pieces
    per partition round-by-round (reference push_based_shuffle.py):
    results identical, intermediate pieces GC-able per round."""
    from ray_tpu.data import shuffle as sh

    n_blocks = sh.PUSH_SHUFFLE_THRESHOLD + 9  # forces the push topology
    ds = rdata.from_items(list(range(1000)), parallelism=n_blocks)
    assert ds.num_blocks() > sh.PUSH_SHUFFLE_THRESHOLD

    from ray_tpu.data.block import block_rows

    srt = ds.sort()
    rows = [r for b in srt.iter_batches() for r in block_rows(b)]
    assert rows == sorted(range(1000))

    shuf = ds.random_shuffle(seed=3)
    rows2 = [r for b in shuf.iter_batches() for r in block_rows(b)]
    assert sorted(rows2) == list(range(1000))
    assert rows2 != list(range(1000))  # actually permuted
