"""New datasources (reference data/datasource breadth: images,
TFRecords, binary files, row-group-partitioned parquet) + the Dataset
method tail (take_batch, train_test_split, to_arrow)."""

import os
import struct

import numpy as np
import pytest

from ray_tpu import data as rdata
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def test_read_images(cluster, tmp_path):
    from PIL import Image

    for i in range(3):
        arr = np.full((10, 12, 3), i * 40, np.uint8)
        Image.fromarray(arr).save(tmp_path / f"img_{i}.png")
    ds = rdata.read_images(str(tmp_path / "*.png"), size=(6, 5))
    imgs = ds.to_pandas()
    assert len(imgs) == 3
    shapes = {im.shape for im in imgs["image"]}
    assert shapes == {(5, 6, 3)}  # PIL size=(W,H) -> array (H,W,C)
    assert all(p.endswith(".png") for p in imgs["path"])


# -- tf.train.Example wire encoding, written BY HAND so the test does
# not trust the parser it is testing --

def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _ld(fno: int, payload: bytes) -> bytes:  # length-delimited field
    return _varint((fno << 3) | 2) + _varint(len(payload)) + payload


def _example(features: dict) -> bytes:
    entries = b""
    for name, (kind, values) in features.items():
        if kind == "bytes":
            inner = b"".join(_ld(1, v) for v in values)
            feat = _ld(1, inner)
        elif kind == "float":
            packed = struct.pack(f"<{len(values)}f", *values)
            feat = _ld(2, _ld(1, packed))
        elif kind == "int64":
            packed = b"".join(_varint(v & ((1 << 64) - 1))
                              for v in values)
            feat = _ld(3, _ld(1, packed))
        entry = _ld(1, name.encode()) + _ld(2, feat)
        entries += _ld(1, entry)
    return _ld(1, entries)  # Example.features


def _write_tfrecord(path, records):
    with open(path, "wb") as f:
        for r in records:
            f.write(struct.pack("<Q", len(r)) + b"\0\0\0\0")
            f.write(r + b"\0\0\0\0")


def test_parse_tf_example_roundtrip():
    rec = _example({
        "label": ("int64", [3, -1]),
        "score": ("float", [0.5, 2.25]),
        "name": ("bytes", [b"abc"]),
    })
    got = rdata.parse_tf_example(rec)
    assert got["label"] == [3, -1]
    assert got["score"] == [0.5, 2.25]
    assert got["name"] == [b"abc"]


def test_read_tfrecords(cluster, tmp_path):
    recs = [_example({"x": ("int64", [i]),
                      "w": ("float", [float(i) / 2])})
            for i in range(5)]
    _write_tfrecord(tmp_path / "a.tfrecord", recs[:3])
    _write_tfrecord(tmp_path / "b.tfrecord", recs[3:])
    ds = rdata.read_tfrecords(str(tmp_path / "*.tfrecord"))
    rows = sorted(ds.take_all(), key=lambda r: r["x"][0])
    assert [r["x"] for r in rows] == [[i] for i in range(5)]
    assert rows[4]["w"] == [2.0]
    # raw mode: bytes round-trip exactly
    raw = rdata.read_tfrecords(str(tmp_path / "a.tfrecord"),
                               parse_examples=False).take_all()
    assert raw == recs[:3]


def test_read_binary_files(cluster, tmp_path):
    (tmp_path / "x.bin").write_bytes(b"\x01\x02")
    (tmp_path / "y.bin").write_bytes(b"\x03")
    df = rdata.read_binary_files(str(tmp_path / "*.bin")).to_pandas()
    assert sorted(df["bytes"]) == [b"\x01\x02", b"\x03"]


def test_read_parquet_partitioned(cluster, tmp_path):
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    df = pd.DataFrame({"a": list(range(100))})
    pq.write_table(pa.Table.from_pandas(df),
                   tmp_path / "p.parquet", row_group_size=25)
    ds = rdata.read_parquet_partitioned(str(tmp_path / "p.parquet"))
    assert ds.num_blocks() == 4  # one read task per row group
    assert sorted(ds.to_pandas()["a"]) == list(range(100))


def test_take_batch_and_train_test_split(cluster):
    ds = rdata.from_items(list(range(50)), parallelism=5)
    assert ds.take_batch(7) == list(range(7))
    train, test = ds.train_test_split(0.2)
    assert train.count() == 40 and test.count() == 10
    assert sorted(train.take_all() + test.take_all()) == list(range(50))
    # tabular: take_batch returns a DataFrame
    import pandas as pd

    dft = rdata.from_pandas(pd.DataFrame({"v": range(30)}))
    out = dft.take_batch(4)
    assert isinstance(out, pd.DataFrame) and list(out["v"]) == [0, 1, 2, 3]


def test_to_arrow(cluster):
    import pandas as pd

    ds = rdata.from_pandas(pd.DataFrame({"v": range(12)}))
    t = ds.to_arrow()
    assert t.num_rows == 12
    assert sorted(t.column("v").to_pylist()) == list(range(12))
