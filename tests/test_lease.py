"""Direct-task lease caching (reference direct_task_transport.h:110:
lease a granted worker per SchedulingKey, push repeat tasks straight to
it, return on idle TTL; worker death falls back to queued retry)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private import api as _api
from ray_tpu._private import config as cfg
from ray_tpu.cluster_utils import Cluster


_cluster_ref = None


@pytest.fixture(scope="module")
def cluster():
    global _cluster_ref
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    _cluster_ref = c
    yield c
    c.shutdown()


def _agent():
    return _cluster_ref.head_agent


@ray_tpu.remote
def _pid():
    return os.getpid()


def test_repeat_tasks_ride_one_lease(cluster):
    ray_tpu.get(_pid.remote(), timeout=60)  # warm: grant the lease
    pids = [ray_tpu.get(_pid.remote(), timeout=60) for _ in range(10)]
    # sequential same-shape tasks ride the cached lease; a rare re-grant
    # (e.g. a renew racing the TTL) may switch workers once
    dominant = max(pids.count(p) for p in set(pids))
    assert dominant >= 9, f"lease reuse broken: {pids}"
    assert len(_agent().leases) >= 1
    w = _api._get_worker()
    assert len(w._lease_cache) >= 1


def test_lease_expires_and_frees_resources(cluster):
    ray_tpu.get(_pid.remote(), timeout=60)
    agent = _agent()
    assert agent.leases
    deadline = time.time() + cfg.get("worker_lease_ttl_s") + 10
    while time.time() < deadline and agent.leases:
        time.sleep(0.5)
    assert not agent.leases, "lease never expired"
    # resources back in the pool
    assert agent.resources_available.get("CPU") == \
        agent.resources_total.get("CPU")


def test_parallel_burst_mixes_lease_and_queue(cluster):
    @ray_tpu.remote
    def slow(i):
        time.sleep(0.2)
        return i

    out = ray_tpu.get([slow.remote(i) for i in range(8)], timeout=120)
    assert out == list(range(8))


def test_leased_worker_death_retries(cluster, tmp_path):
    marker = tmp_path / "died_once"

    @ray_tpu.remote(max_retries=2)
    def fragile():
        import os as _os

        if not marker.exists():
            marker.write_text("x")
            _os._exit(1)  # die mid-task on the leased worker
        return "recovered"

    ray_tpu.get(_pid.remote(), timeout=60)  # warm a lease
    assert ray_tpu.get(fragile.remote(), timeout=120) == "recovered"


def test_lease_skips_pg_and_strategy_tasks(cluster):
    w = _api._get_worker()
    spec = {"pg_id": b"x", "resources": {"CPU": 1}}
    assert w._lease_key(spec) is None
    assert w._lease_key({"scheduling_strategy": "SPREAD"}) is None
    assert w._lease_key({"resources": {"CPU": 1}, "deps": []}) is not None
