"""Async actors + concurrency groups.

Reference: src/ray/core_worker/transport/actor_scheduling_queue.cc,
concurrency_group_manager.cc, fiber.h — coroutine actor methods run
concurrently on an in-worker event loop bounded by max_concurrency;
named concurrency groups give methods dedicated bounded thread pools.
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 4, "memory": 4 * 2**30})
    c.connect()
    yield c
    c.shutdown()


@ray_tpu.remote(max_concurrency=8)
class AsyncActor:
    def __init__(self):
        self.peak = 0
        self.live = 0

    async def sleepy(self, dt):
        self.live += 1
        self.peak = max(self.peak, self.live)
        await asyncio.sleep(dt)
        self.live -= 1
        return dt

    async def peak_seen(self):
        return self.peak

    def sync_method(self, x):
        return x + 1


def test_async_methods_overlap(cluster):
    a = AsyncActor.remote()
    ray_tpu.get(a.sleepy.remote(0.01))  # warm: creation + client connect
    t0 = time.monotonic()
    out = ray_tpu.get([a.sleepy.remote(0.4) for _ in range(8)])
    elapsed = time.monotonic() - t0
    assert out == [0.4] * 8
    # 8 x 0.4s sleeps serially = 3.2s; concurrent they overlap
    assert elapsed < 2.0, f"async calls did not overlap ({elapsed:.2f}s)"
    assert ray_tpu.get(a.peak_seen.remote()) >= 4


def test_async_concurrency_bounded(cluster):
    a = AsyncActor.options().remote()
    ray_tpu.get([a.sleepy.remote(0.1) for _ in range(20)])
    assert ray_tpu.get(a.peak_seen.remote()) <= 8


def test_sync_method_on_async_actor(cluster):
    a = AsyncActor.remote()
    assert ray_tpu.get(a.sync_method.remote(41)) == 42


def test_async_exception_propagates(cluster):
    @ray_tpu.remote
    class Boom:
        async def go(self):
            raise ValueError("async boom")

    b = Boom.remote()
    with pytest.raises(Exception, match="async boom"):
        ray_tpu.get(b.go.remote())


@ray_tpu.remote(concurrency_groups={"io": 4})
class GroupedActor:
    def __init__(self):
        self.io_live = 0
        self.io_peak = 0
        self.log = []

    @ray_tpu.method(concurrency_group="io")
    def fetch(self, dt):
        self.io_live += 1
        self.io_peak = max(self.io_peak, self.io_live)
        time.sleep(dt)
        self.io_live -= 1
        return "io"

    def compute(self, tag):
        self.log.append(tag)
        return tag

    def stats(self):
        return self.io_peak, list(self.log)


def test_concurrency_group_parallelism(cluster):
    g = GroupedActor.remote()
    ray_tpu.get(g.fetch.remote(0.01))  # warm: creation + client connect
    t0 = time.monotonic()
    out = ray_tpu.get([g.fetch.remote(0.4) for _ in range(4)])
    elapsed = time.monotonic() - t0
    assert out == ["io"] * 4
    assert elapsed < 1.3, f"io group did not run concurrently ({elapsed:.2f}s)"
    peak, _ = ray_tpu.get(g.stats.remote())
    assert peak >= 2


def test_default_group_stays_ordered(cluster):
    g = GroupedActor.remote()
    # default (un-grouped) calls keep the single-threaded ordered queue even
    # while the io group churns
    refs = [g.fetch.remote(0.05) for _ in range(3)]
    order = [g.compute.remote(i) for i in range(10)]
    ray_tpu.get(refs + order)
    _, log = ray_tpu.get(g.stats.remote())
    assert log == list(range(10))


def test_method_options_group_override(cluster):
    g = GroupedActor.remote()
    # route a normally-default method through the io pool explicitly
    out = ray_tpu.get(
        [g.compute.options(concurrency_group="io").remote("x")] * 1
    )
    assert out == ["x"]


def test_undeclared_group_errors(cluster):
    g = GroupedActor.remote()
    with pytest.raises(Exception, match="undeclared concurrency group"):
        ray_tpu.get(g.compute.options(concurrency_group="oi").remote(1))


@ray_tpu.remote(max_concurrency=8, concurrency_groups={"serial": 1})
class AsyncGrouped:
    def __init__(self):
        self.live = 0
        self.peak = 0

    @ray_tpu.method(concurrency_group="serial")
    async def one_at_a_time(self, dt):
        self.live += 1
        self.peak = max(self.peak, self.live)
        await asyncio.sleep(dt)
        self.live -= 1
        return self.peak


def test_async_method_bounded_by_its_group(cluster):
    a = AsyncGrouped.remote()
    peaks = ray_tpu.get([a.one_at_a_time.remote(0.05) for _ in range(6)])
    # the group's limit (1) wins over max_concurrency (8)
    assert max(peaks) == 1


def test_inherited_method_group_annotation(cluster):
    # classes defined in-function so cloudpickle ships the base by value
    class Base:
        @ray_tpu.method(concurrency_group="io")
        def inherited_fetch(self, dt):
            time.sleep(dt)
            return "base-io"

    @ray_tpu.remote(concurrency_groups={"io": 3})
    class Derived(Base):
        def other(self):
            return "other"

    d = Derived.remote()
    ray_tpu.get(d.inherited_fetch.remote(0.01))  # warm
    t0 = time.monotonic()
    out = ray_tpu.get([d.inherited_fetch.remote(0.3) for _ in range(3)])
    assert out == ["base-io"] * 3
    assert time.monotonic() - t0 < 0.85  # ran on the 3-wide io pool
