"""Runtime performance floors (reference release/microbenchmark analog).

Conservative floors (~5-10x below measured-on-dev-box, see
RUNTIME_BENCH.json) so load/CI noise doesn't flake, but a pathological
regression — a serialization bug, an accidental sync point, a fork storm —
fails loudly. VERDICT r2 item 3.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture(scope="module")
def cluster():
    c = Cluster(head_resources={"CPU": 8, "memory": 8 * 2**30})
    c.connect()
    yield c
    c.shutdown()


def _rate(fn, n):
    fn()  # warmup
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return n / (time.perf_counter() - t0)


def test_put_get_floors(cluster):
    kb = np.zeros(1024, dtype=np.uint8)
    ref = ray_tpu.put(b"ok")
    assert _rate(lambda: ray_tpu.get(ref), 200) > 60_000  # measured ~320k/s
    assert _rate(lambda: ray_tpu.put(kb), 100) > 3_000  # measured ~16k/s
    mb = np.zeros(1024 * 1024, dtype=np.uint8)
    # single-copy put + async seal announce: measured ~1.6k/s in this
    # GIL-shared fixture (~2.4k/s standalone vs the 790/s baseline); the
    # floor pins the zero-copy path — the old double-copy+sync-announce
    # path measured ~860/s here and would fail it
    assert _rate(lambda: ray_tpu.put(mb), 100) > 1_000  # measured ~1.6k/s


def test_put_get_bandwidth_floor(cluster):
    """Large-object put+get, the weight-publishing path: one memcpy into
    the shm segment on put, zero-copy view on get. Measured ~6.5 GB/s
    warm in this fixture (the old path: ~1.3-3 GB/s)."""
    big = np.zeros(192 * 1024 * 1024, dtype=np.uint8)

    def put_get():
        r = ray_tpu.put(big)
        out = ray_tpu.get(r, timeout=60)
        assert out.nbytes == big.nbytes
        del out
        ray_tpu.free([r])

    put_get()  # warm the segment pages
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        put_get()
        best = max(best, big.nbytes / (time.perf_counter() - t0))
    assert best > 3.0e9, f"put+get bandwidth {best/1e9:.2f} GB/s"


def _recorded_bench():
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "..",
                        "RUNTIME_BENCH.json")
    with open(path) as f:
        return {r["name"]: r for r in json.load(f)["results"]}


def test_recorded_bench_meets_2x_baseline():
    """The committed RUNTIME_BENCH.json must hold the ISSUE-9 acceptance
    ratios over the pre-zero-copy baseline: put 1MB >= 2x 790 ops/s and
    put+get 1GB >= 2x 1.2 GB/s."""
    by_name = {n: r["per_s"] for n, r in _recorded_bench().items()}
    assert by_name["put 1MB"] >= 2 * 790
    assert by_name["put+get 1GB (GB/s)"] >= 2 * 1.2


def test_recorded_serve_pool_scaling_floors():
    """ISSUE-10 acceptance: the committed 2-replica LLM pool bench must
    hold >= 1.6x the single-replica aggregate tokens/s on the same
    host, with TTFT p99 recorded and bounded under concurrency 32, and
    the prefix-cache configuration must show real hits."""
    rec = _recorded_bench()
    r1 = rec["serve pool decode (1 replica)"]
    r2 = rec["serve pool decode (2 replicas)"]
    rp = rec["serve pool decode (2 replicas + prefix cache)"]
    assert r2["per_s"] >= 1.6 * r1["per_s"], (
        f"2-replica aggregate {r2['per_s']} < 1.6x single "
        f"{r1['per_s']}")
    for r in (r1, r2, rp):
        assert r["concurrency"] >= 32
        assert r["ttft_p99_s"] is not None
        # bounded: a p99 blowup (queue collapse) is the failure this
        # floor exists to catch; generous vs the ~0.8s recorded
        assert r["ttft_p99_s"] < 10.0
    assert rp["prefix_hit_rate"] is not None
    assert rp["prefix_hit_rate"] >= 0.5


def test_recorded_serve_spec_family_floors():
    """ISSUE-19 acceptance: the committed `serve_spec` family must show
    speculative decoding paying off under the emulated 50ms chunk
    dispatch — depth-4 spec-on >= 1.5x spec-off tokens/s on the
    sampled arm (acceptance ~0.45 with the random-weight tiny model's
    1-layer draft; greedy acceptance is too low on random weights to
    carry the throughput floor, so it carries the correctness floor
    instead) — and every spec record must be bit-identical to its
    spec-off baseline (``match_baseline``), which is the whole
    draft/verify contract: speculation changes latency, never tokens."""
    rec = _recorded_bench()
    off_s = rec["serve spec decode off (sampled)"]
    d2_s = rec["serve spec decode depth 2 (sampled)"]
    d4_s = rec["serve spec decode depth 4 (sampled)"]
    assert d4_s["per_s"] >= 1.5 * off_s["per_s"], (
        f"depth-4 sampled {d4_s['per_s']} < 1.5x spec-off "
        f"{off_s['per_s']}")
    assert d2_s["per_s"] >= 1.2 * off_s["per_s"], (d2_s, off_s)
    for tag in ("depth 2", "depth 4"):
        for arm in ("greedy", "sampled"):
            r = rec[f"serve spec decode {tag} ({arm})"]
            assert r["match_baseline"] is True, r
            assert r["acceptance_rate"] is not None, r
            assert r["chunk_delay_s"] == 0.05, r


def test_recorded_rl_family_floors():
    """ISSUE-12 satellite: the committed `rl` runtime_perf family must
    exist with sane floors — rollout tokens/s through the sampled
    streaming surface, experience bytes/s through the store, and a
    bounded publish-to-adoption latency (the weight staleness window)."""
    rec = _recorded_bench()
    roll = rec["rl rollout sampled stream (2 replicas)"]
    assert roll["unit"] == "tokens/s"
    # measured ~105 tok/s on the dev box (per-request polling surface,
    # emulated 50ms chunk dispatch); floor well under
    assert roll["per_s"] >= 40, roll
    xfer = rec["rl experience handoff (put+add+claim+get)"]
    # measured ~125 ops/s (~6.4 MB/s of trajectory arrays)
    assert xfer["per_s"] >= 40, xfer
    assert xfer["mb_per_s"] >= 1.0, xfer
    pub = rec["rl weight publish-to-adoption (2 replicas)"]
    # measured ~40ms for a tiny-model tree across 2 replicas; the
    # bound is what keeps "bounded staleness" an enforceable claim
    assert pub["latency_s"] <= 2.0, pub


def test_recorded_transfer_family_floors():
    """ISSUE-17 acceptance: the committed `transfer` family must show
    the receive-side zero-copy data plane paying off — cross-node 64MB
    pull >= 0.9 GB/s (>= 2x the 0.34 recorded before scatter-read +
    pre-faulted segments), scatter-on beating scatter-off in every
    tier, the 1GB tier completing (the serve-pin leak once stranded
    7x64MB and OOM'd it), and the real consumers (weight broadcast,
    prefill->decode KV handoff) recorded with bounded latency."""
    rec = _recorded_bench()
    seq = rec["cross-node pull 64MB (sequential depth=1)"]
    assert seq["gb_per_s"] >= 0.9, seq
    pipe = rec["cross-node pull 64MB (1 source)"]
    off = rec["cross-node pull 64MB (scatter off)"]
    assert pipe["gb_per_s"] >= off["gb_per_s"], (pipe, off)
    g_on = rec["cross-node pull 1GB (scatter on)"]
    g_off = rec["cross-node pull 1GB (scatter off)"]
    assert g_on["gb_per_s"] >= g_off["gb_per_s"], (g_on, g_off)
    assert g_on["gb_per_s"] >= 0.3, g_on
    # consumer adoption latencies: generous bounds (the recorded
    # numbers are ~16ms and ~113ms) — the floor pins that both paths
    # exist and stay interactive, not the exact figure
    pub = rec["transfer weight publish-to-adoption (2 replicas)"]
    assert pub["latency_s"] <= 2.0, pub
    assert pub["weight_bytes"] > 0, pub
    kv = rec["transfer kv handoff (prefill to decode, 1 token)"]
    assert kv["latency_s"] <= 2.0, kv


def test_recorded_qos_family_floors():
    """ISSUE-16 acceptance: the committed `qos` runtime_perf family must
    hold the multi-tenant contention floors — with the pacer ON and a
    learner gang + bulk spill saturating the host, the serving tenant
    keeps >= 0.7x its uncontended decode tokens/s and TTFT p99 within
    2x uncontended, the bulk transfer still completes byte-identical,
    and byte attribution stays within 1%. The batched stream fanout
    must beat the old per-request poll ceiling (~106 tok/s) with well
    under one replica poll RPC per emitted token."""
    rec = _recorded_bench()
    grant = rec["qos pacer grant (unlimited fast path)"]
    # measured ~500k grants/s on the dev box: the tally fast path every
    # tagged send pays when enforcement is off costs ~2us
    assert grant["per_s"] >= 50_000, grant
    cont = rec["qos serve contention (gang + bulk spill, paced)"]
    assert cont["ratio_tokens"] >= 0.7, cont
    assert cont["ratio_ttft"] <= 2.0, cont
    assert cont["bulk_completed"] is True, cont
    assert cont["attribution_err"] <= 0.01, cont
    assert cont["pacer_parks"] > 0, cont  # pacing actually engaged
    assert cont["rate_mbps"] > 0, cont
    fan = rec["qos batched stream fanout (8 streams)"]
    # measured ~640 tok/s aggregate (dev box); the pre-batching surface
    # capped each stream near ~106 tok/s and cost ~3 RPCs/token
    assert fan["per_s"] >= 150, fan
    assert fan["polls_per_token"] <= 1.0, fan


def test_pipelined_pull_2x_sequential_under_latency():
    """Cross-node pull with the chunk window vs one-request-at-a-time,
    under a deterministic injected per-chunk serve latency (the
    fault-injection site standing in for real cross-host RTT, which
    loopback cannot exhibit): the pipeline must hide >= half of it."""
    import os as _os

    from ray_tpu._private import config as cfg
    from ray_tpu._private import fault_injection
    from ray_tpu.cluster_utils import Cluster

    # agents only, no driver (a connect() would clobber the module
    # cluster fixture's global worker)
    c = Cluster(head_resources={"CPU": 2, "memory": 2 * 2**30},
                store_capacity=256 * 2**20)
    c.add_node(resources={"CPU": 2, "memory": 2 * 2**30})
    old_chunk = cfg.get("object_transfer_chunk_bytes")
    try:
        cfg.set_system_config({"object_transfer_chunk_bytes": 256 * 1024})
        src, dst = c.agents[0], c.agents[1]
        data = _os.urandom(4 * 2**20)  # 16 chunks
        fault_injection.configure([
            {"site": "object.read_chunk", "action": "delay",
             "delay_s": 0.01, "count": 0},  # every chunk, 10ms "RTT"
        ])

        def timed_pull(depth):
            cfg.set_system_config({"transfer_pull_pipeline_depth": depth})
            oid = _os.urandom(16)
            src.store.put_bytes(oid, data, metadata=b"")
            c.io.run(src.rpc_object_sealed(
                None, {"object_id": oid, "size": len(data)}))
            t0 = time.perf_counter()
            ok = c.io.run(dst.rpc_fetch_object(
                None, {"object_id": oid, "timeout": 60}))
            dt = time.perf_counter() - t0
            assert ok
            buf = dst.store.get(oid)
            assert bytes(buf.data) == data
            buf.release()
            return dt

        seq = min(timed_pull(1) for _ in range(2))
        pipe = min(timed_pull(8) for _ in range(2))
        assert seq / pipe >= 2.0, (
            f"pipelined pull only {seq/pipe:.2f}x sequential "
            f"({pipe:.3f}s vs {seq:.3f}s)")
    finally:
        fault_injection.clear()
        cfg.set_system_config({
            "object_transfer_chunk_bytes": old_chunk,
            "transfer_pull_pipeline_depth": 8,
        })
        c.shutdown()


def test_recorded_pipeline_family_floors():
    """ISSUE-18 acceptance: the committed `pipeline` runtime_perf family
    must hold the MPMD pipeline floors — a 2-stage 1F1B pipeline makes
    real forward progress through the paced p2p lanes (steps/s and
    boundary hops/s floors ~5x under the dev-box numbers) and its
    measured bubble fraction (p2p-wait + allreduce-wait over wall) stays
    bounded: above the analytic (S-1)/(M+S-1) lower bound, and well
    under the no-overlap ceiling a sequential send-wait-compute loop
    would show."""
    rec = _recorded_bench()
    pipe = rec["pipeline 2-stage 1f1b (steps/s)"]
    # measured ~3.4 steps/s on the dev box (10 steps, 8 microbatches,
    # 256x256 matmul stages, gang spawn + rendezvous included)
    assert pipe["per_s"] >= 0.5, pipe
    assert pipe["heals"] == 0 and pipe["gang_restarts"] == 0, pipe
    analytic = pipe["bubble_analytic"]
    assert abs(analytic - 1 / 9) < 1e-3, pipe
    # measured 0.39 on the dev box: transport overhead rides on top of
    # the analytic schedule bubble, but overlap keeps it far from the
    # ~1.0 a fully-serialized pipeline would record
    assert analytic <= pipe["bubble_measured"] <= 0.75, pipe
    hops = rec["pipeline stage-boundary hops (microbatches/s)"]
    # measured ~54 hops/s (2 x 8 mbs x 10 steps over the same wall)
    assert hops["per_s"] >= 8, hops


def test_recorded_colocate_family_floors():
    """ISSUE-20 acceptance: the committed `colocate` runtime_perf family
    must hold the train+serve-on-one-cluster floors — the gang's
    allreduce step stays within a bounded colocation tax while a
    two-tenant pool decodes on the same host (both tenants keeping a
    live TTFT), and at 2x overcommit the guardian actually walks the
    ladder to L3, sheds typed without starving the pool, and recovers
    to L0 once the flood stops (no parked degradation)."""
    rec = _recorded_bench()
    colo = rec["colocate train step (gang + 2-tenant pool)"]
    # measured 1.18x on the dev box: the serve pool costs the gang
    # under 20% step time; 2.5x is the "colocation is broken" line
    assert colo["step_ratio"] <= 2.5, colo
    assert colo["ttft_p99_a_s"] <= 5.0, colo
    assert colo["ttft_p99_b_s"] <= 5.0, colo
    assert colo["served"] >= 8, colo
    shed = rec["colocate shed rate (2x overcommit, 1 replica)"]
    # measured 0.53 shed rate: the flood is genuinely past capacity
    # (sheds happen) but admission keeps the pool serving (oks happen)
    assert shed["shed"] > 0 and shed["served"] > 0, shed
    assert 0.05 <= shed["shed_rate"] <= 0.95, shed
    assert shed["peak_level"] == 3, shed
    # measured 3.8s back to L0 (fast-dwell knobs): recovery must not
    # park — 30s is the flap/stuck line
    assert shed["recovery_to_l0_s"] is not None, shed
    assert shed["recovery_to_l0_s"] <= 30.0, shed
    assert shed["transitions"] >= 6, shed  # full up AND down ladder


def test_recorded_obs_family_floors():
    """ISSUE-14 acceptance: the committed `obs` runtime_perf family must
    show the always-on flight recorder costing <= 3% on ring allreduce
    and serve decode throughput, with a healthy span-record rate."""
    rec = _recorded_bench()
    spans = rec["obs span record throughput (ring only)"]
    # measured ~820k spans/s on the dev box; even a 5x-slower CI box
    # clears this with room — per-op spans cost microseconds
    assert spans["per_s"] >= 100_000, spans
    for name in ("obs overhead: ring allreduce 16MB (4 ranks)",
                 "obs overhead: serve pool decode (1 replica)"):
        r = rec[name]
        assert r["overhead_pct"] <= 3.0, r
        assert r["baseline_per_s"] > 0, r


def test_live_span_record_throughput_floor():
    """Ring-only record() (the per-chunk hot-path form) must stay
    cheap: >= 50k spans/s live, ~16x under the recorded dev-box rate."""
    import time as _time

    from ray_tpu._private import flight_recorder as fr

    n = 20_000
    t = _time.monotonic()
    fr.record("bench", "warm", t, t, flush=False)
    t0 = _time.perf_counter()
    for _ in range(n):
        fr.record("bench", "floor", t, t, flush=False)
    dt = _time.perf_counter() - t0
    assert n / dt >= 50_000, f"{n / dt:.0f} spans/s"
    # ring stays bounded regardless of volume
    st = fr.stats()
    assert st["ring_len"] <= st["ring_cap"]


def test_task_throughput_floors(cluster):
    @ray_tpu.remote(num_cpus=0)
    def noop():
        return 1

    # spin the pool up before measuring
    ray_tpu.get([noop.remote() for _ in range(32)], timeout=60)

    t0 = time.perf_counter()
    out = ray_tpu.get([noop.remote() for _ in range(500)], timeout=120)
    rate = 500 / (time.perf_counter() - t0)
    assert sum(out) == 500
    # pipelined submission + lease refill + coalesced wire writes:
    # measured ~4.3k/s standalone, ~2.6k/s in this in-process fixture
    # (the head shares the driver GIL here); floor within ~1.5x of the
    # fixture number so a regression toward the r4 ~1.9k/s path fails
    assert rate > 1_800, f"batched task throughput {rate:.0f}/s"

    t0 = time.perf_counter()
    for _ in range(20):
        ray_tpu.get(noop.remote(), timeout=60)
    sync_rate = 20 / (time.perf_counter() - t0)
    assert sync_rate > 650, f"sync task roundtrip {sync_rate:.0f}/s"  # ~1.05k/s


def test_multi_client_throughput_floor(cluster):
    """Aggregate throughput of concurrent worker-owners (each a nested
    driver submitting its own children). r4 shipped a silent regression
    here (509/s aggregate vs 1.9k/s single-client) because no floor
    existed: lease grants + background spawns monopolized the pool and
    queued tasks starved behind lease traffic for seconds."""
    @ray_tpu.remote(num_cpus=0)
    def child():
        return 1

    @ray_tpu.remote(num_cpus=0)
    def owner_batch(n):
        return sum(ray_tpu.get(
            [child.remote() for _ in range(n)], timeout=120))

    ray_tpu.get([owner_batch.remote(50) for _ in range(4)], timeout=120)
    best = 0.0
    for _ in range(3):  # best-of-3: shared-box noise must not flake CI
        t0 = time.perf_counter()
        out = ray_tpu.get([owner_batch.remote(250) for _ in range(4)],
                          timeout=180)
        best = max(best, 1000 / (time.perf_counter() - t0))
        assert sum(out) == 1000
    # measured ~3.2-4.3k/s (r5); r4's starved path was ~0.5k/s
    assert best > 2_200, f"multi-client aggregate {best:.0f}/s"


def test_no_worker_fork_storm(cluster):
    """A flood of zero-cpu tasks must reuse a bounded worker pool, not
    spawn a process per in-flight task (the bug this test pins: 1000
    concurrent num_cpus=0 tasks once spawned 375 workers)."""
    @ray_tpu.remote(num_cpus=0)
    def noop():
        return 1

    agent = cluster.head_agent

    def n_pool():
        return sum(1 for w in agent.workers.values()
                   if w.actor_id is None)

    # nested-owner tests earlier in this shared fixture legitimately
    # leave the pool above cap (blocked-worker backfills linger until
    # the idle cull); the fork-storm invariant is that a flood does not
    # GROW the pool past max(current, cap)
    before = n_pool()
    out = ray_tpu.get([noop.remote() for _ in range(600)], timeout=120)
    assert sum(out) == 600
    assert n_pool() <= max(before, agent._pool_worker_cap())


def test_actor_call_floors(cluster):
    @ray_tpu.remote(num_cpus=0)
    class A:
        def ping(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    t0 = time.perf_counter()
    out = ray_tpu.get([a.ping.remote() for _ in range(500)], timeout=120)
    rate = 500 / (time.perf_counter() - t0)
    assert len(out) == 500
    # fired (non-blocking) actor calls: measured ~8.5k/s (r4)
    assert rate > 2_000, f"actor async call throughput {rate:.0f}/s"


def test_wait_1k_refs_floor(cluster):
    refs = [ray_tpu.put(i) for i in range(1000)]
    t0 = time.perf_counter()
    ready, _ = ray_tpu.wait(refs, num_returns=1000, timeout=60)
    dt = time.perf_counter() - t0
    assert len(ready) == 1000
    assert dt < 2.0, f"wait on 1k local refs took {dt:.2f}s"


def test_collective_family_floors(cluster):
    """The `collective` runtime_perf family's committed invariants, run
    small (4 ranks, 1 MB): per-rank wire bytes for ring allreduce are
    exactly 2·(N−1)/N of the tensor (vs ≥(N−1)·tensor at the star root),
    ring+int8 moves ≤30% of the f32 ring bytes, and throughput floors
    ~5-10x under dev-box measurements (RUNTIME_BENCH.json) so only a
    pathological regression — a per-chunk sync point, a serialization
    storm — trips them."""
    import uuid

    from ray_tpu._private.runtime_perf import _CollRank

    world = 4
    nbytes = 1024 * 1024
    ranks = [_CollRank.remote() for _ in range(world)]
    name = f"floor-{uuid.uuid4().hex[:8]}"

    def run(transport, codec, iters=3):
        outs = ray_tpu.get(
            [a.allreduce_loop.remote(nbytes, iters, transport, codec)
             for a in ranks],
            timeout=300,
        )
        per_op = max(dt for dt, _ in outs)
        return 1.0 / per_op, [b for _, b in outs]

    try:
        ray_tpu.get([a.init.remote(world, r, name)
                     for r, a in enumerate(ranks)], timeout=120)
        star_rate, star_bytes = run("star", None)
        ring_rate, ring_bytes = run("ring", None)
        int8_rate, int8_bytes = run("ring", "int8")

        ring_limit = 2 * (world - 1) / world * nbytes
        for b in ring_bytes:
            assert b <= ring_limit, f"ring rank moved {b} > {ring_limit}"
        # star root re-sends the full reduction to every other rank
        assert max(star_bytes) >= (world - 1) * nbytes
        for b8, bf in zip(int8_bytes, ring_bytes):
            assert b8 <= 0.30 * bf, f"int8 wire {b8} > 30% of f32 {bf}"
        # measured ~30-60/s (ring) and ~25-50/s (star) on the dev box for
        # 1 MB x 4 ranks in this in-process fixture
        assert ring_rate > 3, f"ring 1MB allreduce {ring_rate:.1f}/s"
        assert star_rate > 3, f"star 1MB allreduce {star_rate:.1f}/s"
        assert int8_rate > 3, f"ring+int8 1MB allreduce {int8_rate:.1f}/s"
    finally:
        for a in ranks:
            ray_tpu.kill(a)
